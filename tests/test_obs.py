"""Observability plane: metrics registry, exposition, HTTP endpoint,
Timeline v2 (counter + flow events), the cross-layer wiring, and the
distributed plane (cross-rank aggregation, straggler attribution,
multi-rank timeline merge).

The registry/export tests run on private ``MetricRegistry`` instances so
they are deterministic regardless of what the session's engine has
already recorded into the process-wide default registry; the wiring
tests drive the real engine/serving paths and only assert deltas; the
``integration``-marked tests launch real hvdrun jobs.
"""

import json
import os
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.obs import (
    REGISTRY,
    MetricError,
    MetricRegistry,
    aggregate,
    export,
    server,
)
from horovod_tpu.utils.timeline import Timeline, merge_timelines

N = 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_counter_concurrent_increments():
    reg = MetricRegistry()
    c = reg.counter("t_events_total")
    per_thread = 5000

    def work():
        for _ in range(per_thread):
            c.inc()

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8 * per_thread


def test_counter_rejects_negative_and_gauge_moves_both_ways():
    reg = MetricRegistry()
    with pytest.raises(MetricError):
        reg.counter("c_total").inc(-1)
    g = reg.gauge("g")
    g.set(5)
    g.inc(2)
    g.dec(3)
    assert g.value == 4


def test_histogram_bucket_edges_are_inclusive_upper_bounds():
    reg = MetricRegistry()
    h = reg.histogram("h_seconds", buckets=(1.0, 2.0, 4.0))
    for v in (1.0, 2.0, 2.0001, 5.0):   # edge, edge, just-over, overflow
        h.observe(v)
    [sample] = reg.snapshot()[0]["samples"]
    assert sample["buckets"] == [(1.0, 1), (2.0, 2), (4.0, 3),
                                 (float("inf"), 4)]
    assert sample["count"] == 4
    assert sample["sum"] == pytest.approx(10.0001)


def test_labels_kind_conflicts_and_reset():
    reg = MetricRegistry()
    c = reg.counter("req_total", labelnames=("verb",))
    c.labels(verb="a").inc(2)
    c.labels(verb="b").inc(3)
    assert c.total() == 5
    with pytest.raises(MetricError):
        c.inc()                      # labeled family needs .labels()
    with pytest.raises(MetricError):
        c.labels(wrong="x")
    with pytest.raises(MetricError):
        reg.gauge("req_total")       # kind conflict
    assert reg.counter("req_total", labelnames=("verb",)) is c  # idempotent
    reg.reset()
    assert c.total() == 0
    assert c.labels(verb="a").value == 0  # children survive reset


def test_disable_makes_recording_a_noop():
    reg = MetricRegistry()
    c = reg.counter("c_total")
    h = reg.histogram("h_seconds")
    reg.disable()
    c.inc()
    h.observe(1.0)
    reg.enable()
    c.inc()
    assert c.value == 1 and h.count == 0


# ---------------------------------------------------------------------------
# exposition
# ---------------------------------------------------------------------------

def _golden_registry() -> MetricRegistry:
    reg = MetricRegistry()
    c = reg.counter("req_total", "requests by code", ("code",))
    c.labels(code="200").inc(3)
    c.labels(code="500").inc()
    reg.gauge("depth", "queue depth").set(2.5)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    return reg


GOLDEN = """\
# HELP depth queue depth
# TYPE depth gauge
depth 2.5
# HELP lat_seconds latency
# TYPE lat_seconds histogram
lat_seconds_bucket{le="0.1"} 1
lat_seconds_bucket{le="1"} 2
lat_seconds_bucket{le="+Inf"} 2
lat_seconds_sum 0.55
lat_seconds_count 2
# HELP req_total requests by code
# TYPE req_total counter
req_total{code="200"} 3
req_total{code="500"} 1
"""


def test_prometheus_golden_text():
    text = export.to_prometheus(_golden_registry().snapshot())
    assert text == GOLDEN
    export.validate_prometheus(text)


def test_json_exposition_parses_and_matches():
    blob = json.loads(export.to_json(_golden_registry().snapshot()))
    fams = {m["name"]: m for m in blob["metrics"]}
    assert fams["req_total"]["samples"][0]["value"] == 3
    hist = fams["lat_seconds"]["samples"][0]
    assert hist["count"] == 2 and hist["buckets"][-1] == ["+Inf", 2]


def test_validate_catches_malformed_exposition():
    with pytest.raises(ValueError):
        export.validate_prometheus("no_type_header 1\n")
    with pytest.raises(ValueError):
        export.validate_prometheus("# TYPE x counter\nx 1 2 3\n")


def test_http_endpoint_roundtrip():
    reg = _golden_registry()
    srv = server.MetricsServer(0, addr="127.0.0.1", registry=reg)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        resp = urllib.request.urlopen(f"{base}/metrics", timeout=10)
        assert resp.headers["Content-Type"].startswith(
            "text/plain; version=0.0.4")
        text = resp.read().decode()
        assert text == GOLDEN
        export.validate_prometheus(text)
        blob = json.loads(urllib.request.urlopen(
            f"{base}/metrics.json", timeout=10).read().decode())
        assert {m["name"] for m in blob["metrics"]} == \
            {"req_total", "depth", "lat_seconds"}
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope", timeout=10)
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Timeline v2
# ---------------------------------------------------------------------------

def test_timeline_v2_counter_and_flow_events(tmp_path):
    path = tmp_path / "tl.json"
    with Timeline(str(path)) as tl:
        tl.start_activity("tensor", "QUEUE")
        fid = tl.new_flow()
        tl.flow_start("tensor", fid)
        tl.end_activity("tensor")
        tl.start_activity("tensor", "DISPATCH")
        tl.flow_end("tensor", fid)
        tl.counter("hvd.engine", {"queue_depth": 3, "bytes": 16.0})
        tl.end_activity("tensor")
    events = json.loads(path.read_text())     # Perfetto-parseable JSON
    by_ph = {}
    for ev in events:
        by_ph.setdefault(ev["ph"], []).append(ev)
    assert by_ph["s"][0]["id"] == fid and by_ph["s"][0]["cat"] == "flow"
    assert by_ph["f"][0]["id"] == fid and by_ph["f"][0]["bp"] == "e"
    assert by_ph["C"][0]["args"] == {"queue_depth": 3, "bytes": 16.0}
    assert len(by_ph["B"]) == 2 and len(by_ph["E"]) == 2


def test_timeline_flush_survives_without_close(tmp_path):
    path = tmp_path / "tl.json"
    tl = Timeline(str(path))
    tl.start_activity("t", "QUEUE")
    tl.flush()
    raw = path.read_text()
    assert '"QUEUE"' in raw                   # on disk before close
    # Chrome/Perfetto accept the truncated array (no closing bracket);
    # emulate that tolerance to prove the tail parses.
    events = json.loads(raw.rstrip().rstrip(",") + "]")
    assert any(ev.get("name") == "QUEUE" for ev in events)
    tl.close()


# ---------------------------------------------------------------------------
# cross-layer wiring
# ---------------------------------------------------------------------------

def test_engine_series_and_hvd_metrics_api(tmp_path):
    col = REGISTRY.get("hvd_collectives_total")
    byt = REGISTRY.get("hvd_collective_bytes_total")
    before_n, before_b = col.total(), byt.total()
    tl_path = tmp_path / "tl.json"
    hvd.start_timeline(str(tl_path))
    try:
        x = hvd.per_rank(
            [np.full((16,), float(r), np.float32) for r in range(N)])
        h = hvd.allreduce_async(x, hvd.Average, name="obs.t1")
        hvd.synchronize(h)
    finally:
        hvd.stop_timeline()
    assert col.total() == before_n + 1
    assert byt.total() == before_b + N * 16 * 4
    events = json.loads(tl_path.read_text())
    phs = {ev["ph"] for ev in events}
    assert {"s", "f", "C"} <= phs             # flows + counter tracks
    counter_ev = next(ev for ev in events if ev["ph"] == "C")
    assert counter_ev["args"]["collectives_total"] >= 1
    # hvd.metrics(): all three formats over the same snapshot
    text = hvd.metrics("prometheus")
    export.validate_prometheus(text)
    assert "hvd_collectives_total" in text
    assert "hvd_dispatch_cache_hits_total" in text
    names = {m["name"] for m in hvd.metrics()}
    assert "hvd_collective_bytes_total" in names
    json.loads(hvd.metrics("json"))
    with pytest.raises(ValueError):
        hvd.metrics("xml")


# ---------------------------------------------------------------------------
# distributed plane: aggregation, /cluster, straggler attribution,
# timeline merge
# ---------------------------------------------------------------------------

def test_merge_snapshots_sums_counters_and_labels_ranks():
    regs = []
    for r in range(2):
        reg = MetricRegistry()
        reg.counter("m_events_total", "ev", ("kind",)) \
            .labels(kind="x").inc(r + 1)
        reg.gauge("m_depth").set(r * 5)
        reg.histogram("m_lat_seconds", buckets=(0.1, 1.0)) \
            .observe(0.05 * (r + 1))
        regs.append(reg)
    snaps = [json.loads(aggregate.local_snapshot_blob(
        r, 2, registry=reg).decode()) for r, reg in enumerate(regs)]
    merged = aggregate.merge_snapshots(snaps)
    text = export.to_prometheus(merged)
    export.validate_prometheus(text)
    assert 'm_events_total{kind="x",rank="0"} 1' in text
    assert 'm_events_total{kind="x",rank="1"} 2' in text
    assert 'm_events_total{kind="x"} 3' in text          # cluster sum
    import re
    assert 'm_depth{rank="0"} 0' in text                 # gauges per-rank
    assert 'm_depth{rank="1"} 5' in text
    assert not re.search(r"^m_depth \d", text, re.M)     # no gauge sum
    assert 'm_lat_seconds_count{rank="0"} 1' in text
    assert "m_lat_seconds_count 2" in text               # bucket merge
    assert "horovod_tpu_cluster_ranks_reporting 2" in text
    json.loads(export.to_json(merged))                   # strict JSON


def test_merge_keeps_families_with_own_rank_label_distinct():
    """A family that already owns a 'rank' label (the straggler gauge:
    rank = the straggler) must not collapse into duplicate series when
    several ranks report it — the reporting rank goes to 'from_rank'."""
    regs = []
    for r in range(2):
        reg = MetricRegistry()
        reg.gauge("straggler_age", "g", ("rank", "tensor")) \
            .labels(rank="3", tensor="t").set(10.0 + r)
        regs.append(reg)
    merged = aggregate.merge_snapshots([
        json.loads(aggregate.local_snapshot_blob(
            r, 2, registry=reg).decode())
        for r, reg in enumerate(regs)])
    text = export.to_prometheus(merged)
    export.validate_prometheus(text)
    [fam] = [f for f in merged if f["name"] == "straggler_age"]
    assert "from_rank" in fam["labelnames"]
    series = {(s["labels"]["rank"], s["labels"]["from_rank"]): s["value"]
              for s in fam["samples"]}
    assert series == {("3", "0"): 10.0, ("3", "1"): 11.0}


def test_merge_skips_cluster_histogram_on_divergent_buckets():
    r0, r1 = MetricRegistry(), MetricRegistry()
    r0.histogram("h_seconds", buckets=(0.1, 1.0)).observe(0.5)
    r1.histogram("h_seconds", buckets=(0.2, 2.0)).observe(0.5)
    merged = aggregate.merge_snapshots([
        json.loads(aggregate.local_snapshot_blob(
            r, 2, registry=reg).decode())
        for r, reg in enumerate((r0, r1))])
    [fam] = [f for f in merged if f["name"] == "h_seconds"]
    # per-rank series survive; no merged (rank-less) series is fabricated
    # from incompatible bucket layouts.
    assert all("rank" in s["labels"] for s in fam["samples"])
    export.validate_prometheus(export.to_prometheus(merged))


def test_cluster_metrics_single_process_world():
    """No KV store: the cluster view is the local registry labeled
    rank=<this process> — world size 1, same shape as a real cluster."""
    snap = hvd.cluster_metrics()
    fams = {f["name"]: f for f in snap}
    assert "hvd_collectives_total" in fams
    assert all("rank" in s["labels"]
               for s in fams["hvd_engine_queue_depth"]["samples"])
    bi = fams["horovod_tpu_build_info"]
    live = [s for s in bi["samples"] if s["value"] == 1.0]
    assert live and live[0]["labels"]["version"] == hvd.__version__
    text = hvd.cluster_metrics("prometheus")
    export.validate_prometheus(text)
    assert "horovod_tpu_cluster_ranks_reporting 1" in text
    with pytest.raises(ValueError):
        hvd.cluster_metrics("xml")


def test_cluster_endpoint_served_next_to_metrics():
    """/cluster rides the same server as /metrics once init armed the
    provider (the conftest session already ran hvd.init())."""
    srv = server.MetricsServer(0, addr="127.0.0.1")
    try:
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(
            f"{base}/cluster", timeout=10).read().decode()
        export.validate_prometheus(text)
        assert 'rank="0"' in text
        blob = json.loads(urllib.request.urlopen(
            f"{base}/cluster.json", timeout=10).read().decode())
        assert any(m["name"] == "horovod_tpu_cluster_size"
                   for m in blob["metrics"])
    finally:
        srv.close()


def test_timeline_merge_one_pid_lane_per_rank(tmp_path):
    import time as _time
    paths = []
    for r in range(2):
        p = tmp_path / f"rank{r}.json"
        with Timeline(str(p), rank=r) as tl:
            tl.start_activity("grad.0", "QUEUE")
            fid = tl.new_flow()
            tl.flow_start("grad.0", fid)
            tl.end_activity("grad.0")
            tl.start_activity("grad.0", "DISPATCH")
            tl.flow_end("grad.0", fid)
            tl.counter("hvd.engine", {"queue_depth": r})
            tl.end_activity("grad.0")
        paths.append(str(p))
        _time.sleep(0.02)
    out = tmp_path / "merged.json"
    summary = merge_timelines(str(out), paths)
    assert summary["ranks"] == [0, 1]
    events = json.loads(out.read_text())
    # one pid lane per rank, named and sorted
    assert {e["pid"] for e in events if e["ph"] in "BEC"} == {0, 1}
    names = {e["pid"]: e["args"]["name"] for e in events
             if e.get("name") == "process_name"}
    assert names == {0: "rank 0", 1: "rank 1"}
    # flow arrows survive per rank without aliasing across ranks
    flow = {}
    for e in events:
        if e["ph"] in ("s", "f"):
            flow.setdefault(e["pid"], {})[e["ph"]] = e["id"]
    assert flow[0]["s"] == flow[0]["f"]
    assert flow[1]["s"] == flow[1]["f"]
    assert flow[0]["s"] != flow[1]["s"]
    # counter tracks land in their rank's lane
    assert {e["pid"] for e in events if e["ph"] == "C"} == {0, 1}
    # clock_sync rebase: rank 1 started later, so its spans sit later on
    # the shared axis even though both files' own ts start near 0.
    b0 = min(e["ts"] for e in events if e["pid"] == 0 and e["ph"] == "B")
    b1 = min(e["ts"] for e in events if e["pid"] == 1 and e["ph"] == "B")
    assert b1 > b0


def test_timeline_merge_cli_accepts_truncated_input(tmp_path):
    p0 = tmp_path / "rank0.json"
    tl = Timeline(str(p0), rank=0)
    tl.start_activity("t", "QUEUE")
    tl.flush()                      # crash-truncated: no closing bracket
    p1 = tmp_path / "rank1.json"
    with Timeline(str(p1), rank=1) as tl1:
        tl1.start_activity("t", "QUEUE")
        tl1.end_activity("t")
    out = tmp_path / "m.json"
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.utils.timeline", "merge",
         str(out), str(p0), str(p1)],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "PYTHONPATH":
             REPO + os.pathsep + os.environ.get("PYTHONPATH", "")})
    assert res.returncode == 0, res.stderr
    events = json.loads(out.read_text())
    assert {e["pid"] for e in events if e["ph"] == "B"} == {0, 1}
    tl.close()


def _hvdrun(np_, extra_env=None, timeout=240):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)  # workers force CPU
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_), "--",
         sys.executable, os.path.join(REPO, "tests", "mp_obs_worker.py")],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


@pytest.mark.integration
def test_cluster_view_aggregates_both_ranks_np2():
    """Acceptance: rank 0's /cluster contains both ranks' counters summed
    and the rank label present, and validates as Prometheus."""
    res = _hvdrun(2)
    assert res.returncode == 0, res.stdout + res.stderr
    for r in range(2):
        assert f"rank {r}: CLUSTER-OK" in res.stdout, res.stdout


@pytest.mark.integration
def test_straggler_attribution_np4():
    """Acceptance: a deliberately withheld allreduce at np=4 produces a
    stall report naming the exact lagging rank and tensor."""
    res = _hvdrun(4, extra_env={
        "HVDTPU_TEST_MODE": "stall",
        "HVDTPU_STALL_CHECK_TIME_SECONDS": "2",
        "HVDTPU_STALL_SHUTDOWN_TIME_SECONDS": "4",
    })
    assert res.returncode == 0, res.stdout + res.stderr
    for r in range(3):
        assert f"rank {r}: STRAGGLER-OK" in res.stdout, res.stdout
    assert "rank 3: STRAGGLER-BYSTANDER-OK" in res.stdout, res.stdout
    # the actionable log line names rank + tensor (+ age)
    assert "Straggler: rank(s) 3 have not submitted tensor " \
        "'t.straggle'" in res.stdout, res.stdout


def test_serving_request_metrics_reach_registry():
    import jax

    from horovod_tpu import serving
    from horovod_tpu.models import llama

    ttft = REGISTRY.get("hvd_serving_ttft_seconds")
    reqs = REGISTRY.get("hvd_serving_requests_total")
    before_count = ttft.count
    before_done = reqs.labels(outcome="finished").value

    cfg = llama.LlamaConfig.tiny()            # v256 d64 L2 H4 KV2 fp32
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    sess = serving.serve(params, cfg, num_blocks=16, block_size=8,
                         max_active=2)
    fut = sess.submit(np.arange(5, dtype=np.int32), max_tokens=4)
    sess.drain()
    res = fut.result(timeout=30)
    assert len(res.tokens) == 4
    assert ttft.count == before_count + 1
    assert reqs.labels(outcome="finished").value == before_done + 1
    assert REGISTRY.get("hvd_serving_kv_utilization") is not None
    text = hvd.metrics("prometheus")
    assert "hvd_serving_ttft_seconds_bucket" in text
    assert "hvd_serving_kv_utilization" in text
