"""Observability plane: metrics registry, exposition, HTTP endpoint,
Timeline v2 (counter + flow events), the cross-layer wiring, and the
distributed plane (cross-rank aggregation, straggler attribution,
multi-rank timeline merge).

The registry/export tests run on private ``MetricRegistry`` instances so
they are deterministic regardless of what the session's engine has
already recorded into the process-wide default registry; the wiring
tests drive the real engine/serving paths and only assert deltas; the
``integration``-marked tests launch real hvdrun jobs.
"""

import json
import os
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.obs import (
    REGISTRY,
    MetricError,
    MetricRegistry,
    aggregate,
    export,
    flightrec,
    server,
    slo,
    trace,
)
from horovod_tpu.utils.timeline import Timeline, merge_timelines

N = 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_counter_concurrent_increments():
    reg = MetricRegistry()
    c = reg.counter("t_events_total")
    per_thread = 5000

    def work():
        for _ in range(per_thread):
            c.inc()

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8 * per_thread


def test_counter_rejects_negative_and_gauge_moves_both_ways():
    reg = MetricRegistry()
    with pytest.raises(MetricError):
        reg.counter("c_total").inc(-1)
    g = reg.gauge("g")
    g.set(5)
    g.inc(2)
    g.dec(3)
    assert g.value == 4


def test_histogram_bucket_edges_are_inclusive_upper_bounds():
    reg = MetricRegistry()
    h = reg.histogram("h_seconds", buckets=(1.0, 2.0, 4.0))
    for v in (1.0, 2.0, 2.0001, 5.0):   # edge, edge, just-over, overflow
        h.observe(v)
    [sample] = reg.snapshot()[0]["samples"]
    assert sample["buckets"] == [(1.0, 1), (2.0, 2), (4.0, 3),
                                 (float("inf"), 4)]
    assert sample["count"] == 4
    assert sample["sum"] == pytest.approx(10.0001)


def test_labels_kind_conflicts_and_reset():
    reg = MetricRegistry()
    c = reg.counter("req_total", labelnames=("verb",))
    c.labels(verb="a").inc(2)
    c.labels(verb="b").inc(3)
    assert c.total() == 5
    with pytest.raises(MetricError):
        c.inc()                      # labeled family needs .labels()
    with pytest.raises(MetricError):
        c.labels(wrong="x")
    with pytest.raises(MetricError):
        reg.gauge("req_total")       # kind conflict
    assert reg.counter("req_total", labelnames=("verb",)) is c  # idempotent
    reg.reset()
    assert c.total() == 0
    assert c.labels(verb="a").value == 0  # children survive reset


def test_disable_makes_recording_a_noop():
    reg = MetricRegistry()
    c = reg.counter("c_total")
    h = reg.histogram("h_seconds")
    reg.disable()
    c.inc()
    h.observe(1.0)
    reg.enable()
    c.inc()
    assert c.value == 1 and h.count == 0


# ---------------------------------------------------------------------------
# exposition
# ---------------------------------------------------------------------------

def _golden_registry() -> MetricRegistry:
    reg = MetricRegistry()
    c = reg.counter("req_total", "requests by code", ("code",))
    c.labels(code="200").inc(3)
    c.labels(code="500").inc()
    reg.gauge("depth", "queue depth").set(2.5)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    return reg


GOLDEN = """\
# HELP depth queue depth
# TYPE depth gauge
depth 2.5
# HELP lat_seconds latency
# TYPE lat_seconds histogram
lat_seconds_bucket{le="0.1"} 1
lat_seconds_bucket{le="1"} 2
lat_seconds_bucket{le="+Inf"} 2
lat_seconds_sum 0.55
lat_seconds_count 2
# HELP req_total requests by code
# TYPE req_total counter
req_total{code="200"} 3
req_total{code="500"} 1
"""


def test_prometheus_golden_text():
    text = export.to_prometheus(_golden_registry().snapshot())
    assert text == GOLDEN
    export.validate_prometheus(text)


def test_json_exposition_parses_and_matches():
    blob = json.loads(export.to_json(_golden_registry().snapshot()))
    fams = {m["name"]: m for m in blob["metrics"]}
    assert fams["req_total"]["samples"][0]["value"] == 3
    hist = fams["lat_seconds"]["samples"][0]
    assert hist["count"] == 2 and hist["buckets"][-1] == ["+Inf", 2]


def test_validate_catches_malformed_exposition():
    with pytest.raises(ValueError):
        export.validate_prometheus("no_type_header 1\n")
    with pytest.raises(ValueError):
        export.validate_prometheus("# TYPE x counter\nx 1 2 3\n")


def test_http_endpoint_roundtrip():
    reg = _golden_registry()
    srv = server.MetricsServer(0, addr="127.0.0.1", registry=reg)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        resp = urllib.request.urlopen(f"{base}/metrics", timeout=10)
        assert resp.headers["Content-Type"].startswith(
            "text/plain; version=0.0.4")
        text = resp.read().decode()
        assert text == GOLDEN
        export.validate_prometheus(text)
        blob = json.loads(urllib.request.urlopen(
            f"{base}/metrics.json", timeout=10).read().decode())
        assert {m["name"] for m in blob["metrics"]} == \
            {"req_total", "depth", "lat_seconds"}
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope", timeout=10)
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Timeline v2
# ---------------------------------------------------------------------------

def test_timeline_v2_counter_and_flow_events(tmp_path):
    path = tmp_path / "tl.json"
    with Timeline(str(path)) as tl:
        tl.start_activity("tensor", "QUEUE")
        fid = tl.new_flow()
        tl.flow_start("tensor", fid)
        tl.end_activity("tensor")
        tl.start_activity("tensor", "DISPATCH")
        tl.flow_end("tensor", fid)
        tl.counter("hvd.engine", {"queue_depth": 3, "bytes": 16.0})
        tl.end_activity("tensor")
    events = json.loads(path.read_text())     # Perfetto-parseable JSON
    by_ph = {}
    for ev in events:
        by_ph.setdefault(ev["ph"], []).append(ev)
    assert by_ph["s"][0]["id"] == fid and by_ph["s"][0]["cat"] == "flow"
    assert by_ph["f"][0]["id"] == fid and by_ph["f"][0]["bp"] == "e"
    assert by_ph["C"][0]["args"] == {"queue_depth": 3, "bytes": 16.0}
    assert len(by_ph["B"]) == 2 and len(by_ph["E"]) == 2


def test_timeline_flush_survives_without_close(tmp_path):
    path = tmp_path / "tl.json"
    tl = Timeline(str(path))
    tl.start_activity("t", "QUEUE")
    tl.flush()
    raw = path.read_text()
    assert '"QUEUE"' in raw                   # on disk before close
    # Chrome/Perfetto accept the truncated array (no closing bracket);
    # emulate that tolerance to prove the tail parses.
    events = json.loads(raw.rstrip().rstrip(",") + "]")
    assert any(ev.get("name") == "QUEUE" for ev in events)
    tl.close()


# ---------------------------------------------------------------------------
# cross-layer wiring
# ---------------------------------------------------------------------------

def test_engine_series_and_hvd_metrics_api(tmp_path):
    col = REGISTRY.get("hvd_collectives_total")
    byt = REGISTRY.get("hvd_collective_bytes_total")
    before_n, before_b = col.total(), byt.total()
    tl_path = tmp_path / "tl.json"
    hvd.start_timeline(str(tl_path))
    try:
        x = hvd.per_rank(
            [np.full((16,), float(r), np.float32) for r in range(N)])
        h = hvd.allreduce_async(x, hvd.Average, name="obs.t1")
        hvd.synchronize(h)
    finally:
        hvd.stop_timeline()
    assert col.total() == before_n + 1
    assert byt.total() == before_b + N * 16 * 4
    events = json.loads(tl_path.read_text())
    phs = {ev["ph"] for ev in events}
    assert {"s", "f", "C"} <= phs             # flows + counter tracks
    counter_ev = next(ev for ev in events if ev["ph"] == "C")
    assert counter_ev["args"]["collectives_total"] >= 1
    # hvd.metrics(): all three formats over the same snapshot
    text = hvd.metrics("prometheus")
    export.validate_prometheus(text)
    assert "hvd_collectives_total" in text
    assert "hvd_dispatch_cache_hits_total" in text
    names = {m["name"] for m in hvd.metrics()}
    assert "hvd_collective_bytes_total" in names
    json.loads(hvd.metrics("json"))
    with pytest.raises(ValueError):
        hvd.metrics("xml")


# ---------------------------------------------------------------------------
# distributed plane: aggregation, /cluster, straggler attribution,
# timeline merge
# ---------------------------------------------------------------------------

def test_merge_snapshots_sums_counters_and_labels_ranks():
    regs = []
    for r in range(2):
        reg = MetricRegistry()
        reg.counter("m_events_total", "ev", ("kind",)) \
            .labels(kind="x").inc(r + 1)
        reg.gauge("m_depth").set(r * 5)
        reg.histogram("m_lat_seconds", buckets=(0.1, 1.0)) \
            .observe(0.05 * (r + 1))
        regs.append(reg)
    snaps = [json.loads(aggregate.local_snapshot_blob(
        r, 2, registry=reg).decode()) for r, reg in enumerate(regs)]
    merged = aggregate.merge_snapshots(snaps)
    text = export.to_prometheus(merged)
    export.validate_prometheus(text)
    assert 'm_events_total{kind="x",rank="0"} 1' in text
    assert 'm_events_total{kind="x",rank="1"} 2' in text
    assert 'm_events_total{kind="x"} 3' in text          # cluster sum
    import re
    assert 'm_depth{rank="0"} 0' in text                 # gauges per-rank
    assert 'm_depth{rank="1"} 5' in text
    assert not re.search(r"^m_depth \d", text, re.M)     # no gauge sum
    assert 'm_lat_seconds_count{rank="0"} 1' in text
    assert "m_lat_seconds_count 2" in text               # bucket merge
    assert "horovod_tpu_cluster_ranks_reporting 2" in text
    json.loads(export.to_json(merged))                   # strict JSON


def test_merge_keeps_families_with_own_rank_label_distinct():
    """A family that already owns a 'rank' label (the straggler gauge:
    rank = the straggler) must not collapse into duplicate series when
    several ranks report it — the reporting rank goes to 'from_rank'."""
    regs = []
    for r in range(2):
        reg = MetricRegistry()
        reg.gauge("straggler_age", "g", ("rank", "tensor")) \
            .labels(rank="3", tensor="t").set(10.0 + r)
        regs.append(reg)
    merged = aggregate.merge_snapshots([
        json.loads(aggregate.local_snapshot_blob(
            r, 2, registry=reg).decode())
        for r, reg in enumerate(regs)])
    text = export.to_prometheus(merged)
    export.validate_prometheus(text)
    [fam] = [f for f in merged if f["name"] == "straggler_age"]
    assert "from_rank" in fam["labelnames"]
    series = {(s["labels"]["rank"], s["labels"]["from_rank"]): s["value"]
              for s in fam["samples"]}
    assert series == {("3", "0"): 10.0, ("3", "1"): 11.0}


def test_merge_skips_cluster_histogram_on_divergent_buckets():
    r0, r1 = MetricRegistry(), MetricRegistry()
    r0.histogram("h_seconds", buckets=(0.1, 1.0)).observe(0.5)
    r1.histogram("h_seconds", buckets=(0.2, 2.0)).observe(0.5)
    merged = aggregate.merge_snapshots([
        json.loads(aggregate.local_snapshot_blob(
            r, 2, registry=reg).decode())
        for r, reg in enumerate((r0, r1))])
    [fam] = [f for f in merged if f["name"] == "h_seconds"]
    # per-rank series survive; no merged (rank-less) series is fabricated
    # from incompatible bucket layouts.
    assert all("rank" in s["labels"] for s in fam["samples"])
    export.validate_prometheus(export.to_prometheus(merged))


def test_cluster_metrics_single_process_world():
    """No KV store: the cluster view is the local registry labeled
    rank=<this process> — world size 1, same shape as a real cluster."""
    snap = hvd.cluster_metrics()
    fams = {f["name"]: f for f in snap}
    assert "hvd_collectives_total" in fams
    assert all("rank" in s["labels"]
               for s in fams["hvd_engine_queue_depth"]["samples"])
    bi = fams["horovod_tpu_build_info"]
    live = [s for s in bi["samples"] if s["value"] == 1.0]
    assert live and live[0]["labels"]["version"] == hvd.__version__
    text = hvd.cluster_metrics("prometheus")
    export.validate_prometheus(text)
    assert "horovod_tpu_cluster_ranks_reporting 1" in text
    with pytest.raises(ValueError):
        hvd.cluster_metrics("xml")


def test_cluster_endpoint_served_next_to_metrics():
    """/cluster rides the same server as /metrics once init armed the
    provider (the conftest session already ran hvd.init())."""
    srv = server.MetricsServer(0, addr="127.0.0.1")
    try:
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(
            f"{base}/cluster", timeout=10).read().decode()
        export.validate_prometheus(text)
        assert 'rank="0"' in text
        blob = json.loads(urllib.request.urlopen(
            f"{base}/cluster.json", timeout=10).read().decode())
        assert any(m["name"] == "horovod_tpu_cluster_size"
                   for m in blob["metrics"])
    finally:
        srv.close()


def test_timeline_merge_one_pid_lane_per_rank(tmp_path):
    import time as _time
    paths = []
    for r in range(2):
        p = tmp_path / f"rank{r}.json"
        with Timeline(str(p), rank=r) as tl:
            tl.start_activity("grad.0", "QUEUE")
            fid = tl.new_flow()
            tl.flow_start("grad.0", fid)
            tl.end_activity("grad.0")
            tl.start_activity("grad.0", "DISPATCH")
            tl.flow_end("grad.0", fid)
            tl.counter("hvd.engine", {"queue_depth": r})
            tl.end_activity("grad.0")
        paths.append(str(p))
        _time.sleep(0.02)
    out = tmp_path / "merged.json"
    summary = merge_timelines(str(out), paths)
    assert summary["ranks"] == [0, 1]
    events = json.loads(out.read_text())
    # one pid lane per rank, named and sorted
    assert {e["pid"] for e in events if e["ph"] in "BEC"} == {0, 1}
    names = {e["pid"]: e["args"]["name"] for e in events
             if e.get("name") == "process_name"}
    assert names == {0: "rank 0", 1: "rank 1"}
    # flow arrows survive per rank without aliasing across ranks
    flow = {}
    for e in events:
        if e["ph"] in ("s", "f"):
            flow.setdefault(e["pid"], {})[e["ph"]] = e["id"]
    assert flow[0]["s"] == flow[0]["f"]
    assert flow[1]["s"] == flow[1]["f"]
    assert flow[0]["s"] != flow[1]["s"]
    # counter tracks land in their rank's lane
    assert {e["pid"] for e in events if e["ph"] == "C"} == {0, 1}
    # clock_sync rebase: rank 1 started later, so its spans sit later on
    # the shared axis even though both files' own ts start near 0.
    b0 = min(e["ts"] for e in events if e["pid"] == 0 and e["ph"] == "B")
    b1 = min(e["ts"] for e in events if e["pid"] == 1 and e["ph"] == "B")
    assert b1 > b0


def test_timeline_merge_cli_accepts_truncated_input(tmp_path):
    p0 = tmp_path / "rank0.json"
    tl = Timeline(str(p0), rank=0)
    tl.start_activity("t", "QUEUE")
    tl.flush()                      # crash-truncated: no closing bracket
    p1 = tmp_path / "rank1.json"
    with Timeline(str(p1), rank=1) as tl1:
        tl1.start_activity("t", "QUEUE")
        tl1.end_activity("t")
    out = tmp_path / "m.json"
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.utils.timeline", "merge",
         str(out), str(p0), str(p1)],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "PYTHONPATH":
             REPO + os.pathsep + os.environ.get("PYTHONPATH", "")})
    assert res.returncode == 0, res.stderr
    events = json.loads(out.read_text())
    assert {e["pid"] for e in events if e["ph"] == "B"} == {0, 1}
    tl.close()


def _hvdrun(np_, extra_env=None, timeout=240):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)  # workers force CPU
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_), "--",
         sys.executable, os.path.join(REPO, "tests", "mp_obs_worker.py")],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


@pytest.mark.integration
def test_cluster_view_aggregates_both_ranks_np2():
    """Acceptance: rank 0's /cluster contains both ranks' counters summed
    and the rank label present (incl. SLO gauges + trace counters from
    both ranks), /healthz answers ready, and it validates as
    Prometheus."""
    res = _hvdrun(2)
    assert res.returncode == 0, res.stdout + res.stderr
    for r in range(2):
        assert f"rank {r}: CLUSTER-OK" in res.stdout, res.stdout


@pytest.mark.integration
@pytest.mark.slow
def test_cluster_serving_trace_e2e_np2():
    """Acceptance: same np=2 cluster pass but rank 0's sampled trace is
    one REAL serving request — connected QUEUE→PREFILL→DECODE chain
    sharing a trace id in the Timeline v2 output.  slow-marked for the
    tiny-llama compile; the in-process serving-trace test above covers
    the chain shape in tier-1."""
    res = _hvdrun(2, extra_env={"HVDTPU_OBS_SERVING_E2E": "1"})
    assert res.returncode == 0, res.stdout + res.stderr
    for r in range(2):
        assert f"rank {r}: CLUSTER-OK" in res.stdout, res.stdout


@pytest.mark.integration
def test_tsdb_alerts_and_query_over_cluster_np2():
    """Acceptance (tsdb tier): at np=2 with HVDTPU_ALERTS armed through
    the real config surface, a breached rule fires on BOTH ranks and the
    firing gauges arrive rank-labeled on /cluster; /alertz reports the
    firing state; /query answers over the local sampled history AND the
    fleet history fed by the /cluster merges; a flight-recorder bundle
    carries the alert_fired event and the curated tsdb tail."""
    res = _hvdrun(2, extra_env={"HVDTPU_TEST_MODE": "tsdb"})
    assert res.returncode == 0, res.stdout + res.stderr
    for r in range(2):
        assert f"rank {r}: TSDB-OK" in res.stdout, res.stdout


@pytest.mark.integration
@pytest.mark.slow
def test_healthz_transitions_under_injected_faults_np2():
    """Acceptance (chaos satellite): with a fault spec stalling rank 1's
    negotiation check-in and then injecting a serving-step failure,
    rank 0's /healthz must transition 200 -> 503 -> 200 twice (stall,
    then serving drain window), the aborted request must carry
    finish_reason="error", and rank 1's injected fault must surface
    rank-labeled in hvd_faults_injected_total on /cluster.  slow-marked
    (two runner startups + a tiny-llama compile); the in-process halves
    are tier-1 in test_chaos.py."""
    res = _hvdrun(2, extra_env={
        "HVDTPU_TEST_MODE": "chaos",
        "HVDTPU_HEALTH_MAX_NEGOTIATION_AGE": "1",
    }, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "rank 0: CHAOS-OK" in res.stdout, res.stdout
    assert "rank 1: CHAOS-STALLER-OK" in res.stdout, res.stdout


@pytest.mark.integration
def test_straggler_attribution_np4():
    """Acceptance: a deliberately withheld allreduce at np=4 produces a
    stall report naming the exact lagging rank and tensor."""
    res = _hvdrun(4, extra_env={
        "HVDTPU_TEST_MODE": "stall",
        "HVDTPU_STALL_CHECK_TIME_SECONDS": "2",
        "HVDTPU_STALL_SHUTDOWN_TIME_SECONDS": "4",
    })
    assert res.returncode == 0, res.stdout + res.stderr
    for r in range(3):
        assert f"rank {r}: STRAGGLER-OK" in res.stdout, res.stdout
    assert "rank 3: STRAGGLER-BYSTANDER-OK" in res.stdout, res.stdout
    # the actionable log line names rank + tensor (+ age)
    assert "Straggler: rank(s) 3 have not submitted tensor " \
        "'t.straggle'" in res.stdout, res.stdout


# ---------------------------------------------------------------------------
# request tracing (obs/trace)
# ---------------------------------------------------------------------------

def test_trace_span_chain_export_and_keep_bound():
    tr = trace.Tracer(sample_rate=1.0, keep=4)
    root = tr.start_trace("req", lane="req0", req_id=0)
    q = root.child("QUEUE", prompt_len=5)
    q.end(queue_wait_s=0.0)
    p = root.child("PREFILL", after=q)
    p.event("collective.enqueue", tensor="wo.0")
    p.end()
    root.end(outcome="finished")
    exp = tr.export()
    assert exp["trace_id"] == root.trace_id
    by_name = {s["name"]: s for s in exp["spans"]}
    assert set(by_name) == {"QUEUE", "PREFILL", "req"}
    assert {s["trace_id"] for s in exp["spans"]} == {root.trace_id}
    assert by_name["req"]["parent_id"] is None
    assert by_name["QUEUE"]["parent_id"] == by_name["req"]["span_id"]
    assert by_name["PREFILL"]["parent_id"] == by_name["req"]["span_id"]
    assert by_name["QUEUE"]["attrs"]["queue_wait_s"] == 0.0
    assert by_name["PREFILL"]["events"][0]["name"] == "collective.enqueue"
    assert all(s["duration_s"] >= 0 for s in exp["spans"])
    json.dumps(exp)                        # JSON-exportable by contract
    # finished-trace table is bounded: oldest traces evicted first
    first_id = root.trace_id
    for _ in range(4):
        tr.start_trace("req").end()
    assert len(tr.finished_ids()) == 4
    assert first_id not in tr.finished_ids()
    assert tr.export(first_id) is None


def test_trace_context_propagation_and_idempotent_end():
    tr = trace.Tracer(sample_rate=1.0)
    assert trace.current_span() is None
    root = tr.start_trace("req")
    with root.use():
        assert trace.current_span() is root
        child = root.child("PREFILL")
        with child.use():
            assert trace.current_span() is child
        assert trace.current_span() is root
    assert trace.current_span() is None
    child.end()
    t1 = child.t1
    child.end(ignored=True)                # double-close: no-op
    assert child.t1 == t1 and "ignored" not in child.attrs
    root.end()


def test_trace_unsampled_is_null_span_noop():
    tr = trace.Tracer(sample_rate=0.0)
    sp = tr.start_trace("req")
    assert sp is trace.NULL_SPAN and not sp.sampled and not sp
    assert sp.child("QUEUE") is sp         # every op returns instantly
    with sp.use():
        assert trace.current_span() is None   # never leaks NULL_SPAN
    sp.event("x")
    sp.end()
    assert tr.export() is None and tr.finished_ids() == []


def test_trace_timeline_slices_and_flow_arrows(tmp_path):
    path = tmp_path / "tl.json"
    with Timeline(str(path)) as tl:
        tr = trace.Tracer(sample_rate=1.0)
        root = tr.start_trace("req", lane="req7", timeline=tl)
        q = root.child("QUEUE")
        q.end()
        p = root.child("PREFILL", after=q)  # flow arrow QUEUE -> PREFILL
        p.end()
        root.end()
    events = json.loads(path.read_text())
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"QUEUE", "PREFILL", "req"}
    assert {e["args"]["trace_id"] for e in xs} == {root.trace_id}
    assert all(e["dur"] >= 0 for e in xs)
    links = [e for e in events if e.get("name") == "hvd.link"]
    s = [e for e in links if e["ph"] == "s"]
    f = [e for e in links if e["ph"] == "f"]
    assert len(s) == 1 and len(f) == 1 and s[0]["id"] == f[0]["id"]
    assert f[0]["bp"] == "e"
    # arrow tail sits at QUEUE's end, head at PREFILL's start
    [qx] = [e for e in xs if e["name"] == "QUEUE"]
    [px] = [e for e in xs if e["name"] == "PREFILL"]
    assert s[0]["ts"] == pytest.approx(qx["ts"] + qx["dur"], abs=1.0)
    assert f[0]["ts"] == pytest.approx(px["ts"], abs=1.0)


def test_serving_trace_chain_and_greedy_parity():
    """One request -> one connected QUEUE->PREFILL->DECODE chain sharing
    a trace id; disabling sampling changes nothing about the tokens."""
    import jax

    from horovod_tpu import serving
    from horovod_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    prompt = np.arange(7, dtype=np.int32)

    def run_once():
        with serving.serve(params, cfg, num_blocks=16, block_size=8,
                           max_active=2) as sess:
            fut = sess.submit(prompt, max_tokens=4)
            sess.drain()
            res = fut.result(timeout=30)
            return res, sess.request_trace(res.metrics["req_id"])

    old_rate = trace.TRACER.sample_rate
    try:
        trace.TRACER.sample_rate = 1.0
        res_on, tr = run_once()
        trace.TRACER.sample_rate = 0.0
        res_off, tr_off = run_once()
    finally:
        trace.TRACER.sample_rate = old_rate
    assert res_on.tokens == res_off.tokens          # greedy parity
    assert tr_off is None                           # unsampled: no trace
    assert tr is not None
    assert res_on.metrics["trace_id"] == tr["trace_id"]
    names = [s["name"] for s in tr["spans"]]
    assert {"QUEUE", "PREFILL", "DECODE", "serving.request"} <= set(names)
    assert {s["trace_id"] for s in tr["spans"]} == {tr["trace_id"]}
    [root] = [s for s in tr["spans"] if s["parent_id"] is None]
    assert root["name"] == "serving.request"
    assert all(s["parent_id"] == root["span_id"] for s in tr["spans"]
               if s["parent_id"] is not None)
    # phases land in causal order; root ends last
    order = {s["name"]: s["t_offset_s"] for s in tr["spans"]}
    assert order["QUEUE"] <= order["PREFILL"] <= order["DECODE"]
    assert root["attrs"]["outcome"] == "finished"
    assert root["attrs"]["new_tokens"] == 4


def test_trace_queue_wait_after_preemption_counts_requeue_only():
    """The re-opened QUEUE span of a preempted request is tagged with
    the wait since the preemption, not since the original submit — the
    misattribution would land exactly on the requests where 'why was
    this slow' matters most."""
    from horovod_tpu.serving.kv_pager import KVPager, PagedKVCache
    from horovod_tpu.serving.scheduler import Request, Scheduler

    now = [0.0]
    pager = KVPager(PagedKVCache(n_layers=1, num_blocks=16, block_size=4,
                                 kv_heads=1, head_dim=4))
    s = Scheduler(pager, max_active=2, prefill_token_budget=1000,
                  clock=lambda: now[0])
    old_rate = trace.TRACER.sample_rate
    trace.TRACER.sample_rate = 1.0
    try:
        req = Request(req_id=0, prompt=np.arange(4, dtype=np.int32),
                      max_new_tokens=8)
        req.trace = trace.TRACER.start_trace("req", lane="req0")
        s.submit(req)
        now[0] = 2.0
        assert s.admit() == [req]
        now[0] = 10.0
        req.generated = [1, 2]
        req.context_len = 6
        s.preempt(req)
        now[0] = 11.0
        assert s.admit() == [req]
        s.finish(req)
    finally:
        trace.TRACER.sample_rate = old_rate
    spans = trace.TRACER.export(req.trace.trace_id)["spans"]
    waits = [sp["attrs"]["queue_wait_s"] for sp in spans
             if sp["name"] == "QUEUE"]
    assert waits == [pytest.approx(2.0), pytest.approx(1.0)], waits


# ---------------------------------------------------------------------------
# SLO engine (obs/slo)
# ---------------------------------------------------------------------------

def test_slo_parse_spec_forms_and_errors():
    s = slo.parse_spec("p99(ttft) < 250ms over 5m")
    assert s.metric == "hvd_serving_ttft_seconds"
    assert s.quantile == 0.99
    assert s.threshold_s == pytest.approx(0.25)
    assert s.window_s == 300.0
    assert s.objective == 0.99 and s.budget == pytest.approx(0.01)
    s = slo.parse_spec("p95(itl)<=50ms", name="itl")
    assert s.name == "itl" and s.window_s == 300.0  # default 5m
    s = slo.parse_spec("p50(my_hist_seconds) < 2s over 1h")
    assert s.metric == "my_hist_seconds" and s.window_s == 3600.0
    s = slo.parse_spec("p99.9(queue_wait) < 1s over 30s")
    assert s.quantile == pytest.approx(0.999)
    specs = slo.parse_spec_list(
        "a=p99(ttft) < 250ms over 5m; p95(itl) < 50ms;")
    assert [x.name for x in specs] == ["a", "itl_p95"]
    for bad in ("p99(ttft)", "ttft < 250ms", "p0(ttft) < 1s",
                "p100(ttft) < 1s", "p99(ttft) < 0ms",
                "p99(ttft) < 1parsec"):
        with pytest.raises(slo.SLOError):
            slo.parse_spec(bad)


def test_slo_good_fraction_and_quantile_hand_built():
    edges = (0.1, 0.25, 1.0)
    # 6 obs <= 0.1, 2 in (0.1, 0.25], 1 in (0.25, 1.0], 1 overflow
    cum = [6, 8, 9, 10]
    assert slo.good_fraction(edges, cum, 0.25) == pytest.approx(0.8)
    assert slo.good_fraction(edges, cum, 0.1) == pytest.approx(0.6)
    # interpolation inside (0.1, 0.25]: halfway -> 6 + 2*(0.075/0.15)
    assert slo.good_fraction(edges, cum, 0.175) == pytest.approx(0.7)
    # below the first edge: linear from zero
    assert slo.good_fraction(edges, cum, 0.05) == pytest.approx(0.3)
    # past the last finite edge: overflow obs stay bad (conservative)
    assert slo.good_fraction(edges, cum, 5.0) == pytest.approx(0.9)
    assert slo.good_fraction(edges, [0, 0, 0, 0], 0.1) == 1.0  # no traffic
    # quantiles: same interpolation convention
    assert slo.quantile(edges, cum, 0.6) == pytest.approx(0.1)
    assert slo.quantile(edges, cum, 0.7) == pytest.approx(0.175)
    assert slo.quantile(edges, cum, 0.99) == 1.0   # lands in +Inf: clamp
    assert slo.quantile(edges, [0, 0, 0, 0], 0.5) is None
    assert slo.attainment_of([0.1, 0.2, 0.9], 0.25) == pytest.approx(2 / 3)
    assert slo.attainment_of([], 0.25) == 1.0


def test_slo_engine_burn_rates_windows_and_violations():
    reg = MetricRegistry()
    h = reg.histogram("lat_seconds", buckets=(1.0, 2.0))
    now = [0.0]
    eng = slo.SLOEngine(registry=reg, clock=lambda: now[0], tick_s=1.0,
                        burn_windows=(("fast", 60.0), ("slow", 600.0)))
    eng.add("p90(lat_seconds) < 1s over 60s", name="lat")
    eng.tick()                              # zero baseline at t=0
    for _ in range(18):
        h.observe(0.5)                      # good
    for _ in range(2):
        h.observe(1.5)                      # bad
    now[0] = 30.0
    eng.tick()
    out = eng.evaluate()["lat"]
    # 18/20 good = exactly the 0.9 objective: met, burning the whole
    # budget (burn 1.0) but not over it.
    assert out["attainment"] == pytest.approx(0.9)
    assert out["met"] is True
    assert out["burn_rate"]["fast"] == pytest.approx(1.0)
    v = eng._c_violations.labels(slo="lat")
    assert v.value == 0
    for _ in range(10):
        h.observe(1.5)                      # 12 bad / 30 total
    now[0] = 60.0
    eng.tick()
    out = eng.evaluate()["lat"]
    assert out["attainment"] == pytest.approx(0.6)
    assert out["met"] is False
    assert out["burn_rate"]["fast"] == pytest.approx(4.0)  # 0.4 / 0.1
    assert v.value == 1                    # met -> violated transition
    eng.evaluate()
    assert v.value == 1                    # still violated: no re-count
    # traffic stops; the fast window slides past the bad burst and the
    # SLO recovers (empty window = attainment 1.0), re-arming the edge.
    now[0] = 150.0
    eng.tick()
    out = eng.evaluate()["lat"]
    assert out["attainment"] == 1.0 and out["met"] is True
    assert out["burn_rate"]["fast"] == 0.0
    # gauges landed in the registry (the /metrics + /cluster surface)
    text = export.to_prometheus(reg.snapshot())
    assert 'hvd_slo_attainment{slo="lat"} 1' in text
    assert 'hvd_slo_burn_rate{slo="lat",window="fast"} 0' in text
    assert 'hvd_slo_objective{slo="lat"} 0.9' in text
    assert 'hvd_slo_violations_total{slo="lat"} 1' in text


def test_slo_cum_counts_reads_registry_histograms():
    reg = MetricRegistry()
    h = reg.histogram("cc_seconds", buckets=(0.1, 1.0), labelnames=("k",))
    h.labels(k="a").observe(0.05)
    h.labels(k="b").observe(0.5)
    h.labels(k="b").observe(5.0)
    edges, cum = slo.cum_counts("cc_seconds", reg)
    assert edges == (0.1, 1.0)
    assert cum == [1, 2, 3]                 # children summed, +Inf last
    assert slo.cum_counts("missing", reg) == (None, None)
    reg.counter("not_hist_total").inc()
    assert slo.cum_counts("not_hist_total", reg) == (None, None)


def test_slo_engine_history_stays_bounded():
    reg = MetricRegistry()
    h = reg.histogram("lat_seconds", buckets=(1.0,))
    now = [0.0]
    eng = slo.SLOEngine(registry=reg, clock=lambda: now[0], tick_s=10.0,
                        burn_windows=(("fast", 60.0), ("slow", 600.0)))
    eng.add("p90(lat_seconds) < 1s over 60s", name="lat")
    for i in range(1000):
        h.observe(0.5)
        now[0] = float(i * 10)
        eng.tick()
    snaps = eng._hist["lat_seconds"].snaps
    # horizon = max(window) + 2 ticks = 620s -> ~63 snapshots at 10s
    assert len(snaps) <= 640 / 10 + 3
    assert eng.evaluate()["lat"]["met"] is True


def test_slo_arm_status_disarm_roundtrip():
    eng = slo.arm("rt=p99(ttft) < 250ms over 5m", tick_s=3600)
    try:
        assert eng is not None
        st = slo.status()
        assert st["rt"]["objective"] == 0.99
        assert set(st["rt"]["burn_rate"]) == {"5m", "1h"}
    finally:
        slo.disarm()
    assert slo.status() == {}
    assert slo.arm("   ") is None          # empty spec list: unarmed


# ---------------------------------------------------------------------------
# flight recorder (obs/flightrec)
# ---------------------------------------------------------------------------

def test_flightrec_ring_is_bounded_and_ordered():
    rec = flightrec.FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("tick", name=f"e{i}", i=i)
    assert len(rec) == 8
    snap = rec.snapshot()
    assert [e["name"] for e in snap] == [f"e{i}" for i in range(12, 20)]
    assert all(e["kind"] == "tick" and e["data"]["i"] >= 12 for e in snap)
    assert [e["t_mono_s"] for e in snap] == \
        sorted(e["t_mono_s"] for e in snap)


def test_flightrec_concurrent_appends_stay_bounded():
    rec = flightrec.FlightRecorder(capacity=128)
    n_threads, per_thread = 8, 2000
    before = REGISTRY.get("hvd_flightrec_events_total").total()

    def work(t):
        for i in range(per_thread):
            rec.record("t", name=f"{t}.{i}")

    threads = [threading.Thread(target=work, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(rec) == 128
    assert len(rec.snapshot()) == 128
    assert REGISTRY.get("hvd_flightrec_events_total").total() - before \
        == n_threads * per_thread


def test_flightrec_capacity_resize_and_disable():
    rec = flightrec.FlightRecorder(capacity=8)
    for i in range(8):
        rec.record("e", name=str(i))
    rec.set_capacity(4)                    # shrink keeps the newest
    assert [e["name"] for e in rec.snapshot()] == ["4", "5", "6", "7"]
    rec.set_capacity(16)                   # grow keeps everything held
    assert len(rec) == 4
    rec.set_capacity(0)                    # disable: record is a no-op
    rec.record("e", name="x")
    assert len(rec) == 0 and rec.snapshot() == []


def test_flightrec_dump_bundle_contents(tmp_path):
    class FakeStall:
        missing_ranks = (3, 1)
        age_ms = 2500

    rec = flightrec.FlightRecorder(capacity=16)
    rec.set_identity(0, 4)
    rec.record("stall_warning", desc="t.x")
    path = rec.dump(str(tmp_path / "b.json"), reason="stall_shutdown",
                    stall={"t.x": FakeStall()},
                    extra={"error": "stalled"})
    assert path == str(tmp_path / "b.json")
    bundle = json.loads((tmp_path / "b.json").read_text())
    assert bundle["reason"] == "stall_shutdown"
    assert bundle["rank"] == 0 and bundle["size"] == 4
    assert bundle["events"][0]["kind"] == "stall_warning"
    assert bundle["stall"]["t.x"]["missing_ranks"] == [1, 3]   # sorted
    assert bundle["stall"]["t.x"]["missing_rank_bitmap"] == 0b1010
    assert bundle["stall"]["t.x"]["age_ms"] == 2500
    assert bundle["extra"]["error"] == "stalled"
    assert any(f["name"] == "hvd_flightrec_events_total"
               for f in bundle["metrics"])
    assert not list(tmp_path.glob("*.tmp.*"))   # atomic: no torn files


def test_flightrec_maybe_dump_only_when_armed(tmp_path):
    rec = flightrec.FlightRecorder(capacity=4)
    rec.record("e", name="x")
    assert rec.maybe_dump("round_abort") is None     # unarmed: no file
    rec.arm(str(tmp_path))
    path = rec.maybe_dump("round_abort")
    assert path is not None and os.path.dirname(path) == str(tmp_path)
    assert "round_abort" in os.path.basename(path)
    json.loads(open(path).read())
    rec.arm(None)                                    # disarm again
    assert rec.maybe_dump("round_abort") is None


def test_hvd_flight_record_manual_api(tmp_path):
    path = hvd.flight_record(str(tmp_path / "manual.json"))
    assert path == str(tmp_path / "manual.json")
    bundle = json.loads((tmp_path / "manual.json").read_text())
    assert bundle["reason"] == "manual"
    # the session engine's traffic is visible in the bundle's registry
    assert any(f["name"] == "hvd_collectives_total"
               for f in bundle["metrics"])


# ---------------------------------------------------------------------------
# /healthz + stale-rank aggregation
# ---------------------------------------------------------------------------

def _get_healthz(port):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_healthz_ready_unready_and_provider_failure():
    saved = server._health_provider
    srv = server.MetricsServer(0, addr="127.0.0.1",
                               registry=MetricRegistry())
    try:
        server.set_health_provider(
            lambda: {"ready": True, "status": "ok", "rank": 0, "size": 2})
        code, body = _get_healthz(srv.port)
        assert code == 200 and body["ready"] is True and body["size"] == 2
        server.set_health_provider(lambda: {"ready": False,
                                            "status": "unready"})
        code, body = _get_healthz(srv.port)
        assert code == 503 and body["ready"] is False
        # no provider = the shutdown->init window of an elastic
        # re-rendezvous: answer 503, never 500/404
        server.set_health_provider(None)
        code, body = _get_healthz(srv.port)
        assert code == 503 and "re-rendezvous" in body["reason"]
        # a crashing provider must still answer the probe
        def boom():
            raise RuntimeError("broken provider")
        server.set_health_provider(boom)
        code, body = _get_healthz(srv.port)
        assert code == 503 and "broken provider" in body["reason"]
    finally:
        server.set_health_provider(saved)
        srv.close()


def test_healthz_live_session_is_ready():
    """The conftest session ran hvd.init(): the armed provider reports
    this rank ready with a fresh negotiation age."""
    srv = server.MetricsServer(0, addr="127.0.0.1")
    try:
        code, body = _get_healthz(srv.port)
    finally:
        srv.close()
    assert code == 200, body
    assert body["ready"] is True and body["engine_alive"] is True
    assert body["rank"] == 0 and body["size"] >= 1
    assert body["uptime_s"] > 0
    assert body["last_negotiation_age_s"] >= 0.0


def test_merge_marks_stale_rank_and_excludes_it_from_sums():
    """A rank whose snapshot outlived 2x its publish interval is flagged
    stale, dropped from summed/merged cluster series and from
    ranks_reporting — a dead rank must not mask live stragglers."""
    import time as _time
    snaps = []
    for r in range(2):
        reg = MetricRegistry()
        reg.counter("st_events_total").inc(r + 1)
        reg.histogram("st_lat_seconds", buckets=(0.1, 1.0)).observe(0.05)
        snap = json.loads(aggregate.local_snapshot_blob(
            r, 2, registry=reg,
            extra_meta={"interval_s": 2.0}).decode())
        snaps.append(snap)
    snaps[1]["time"] = _time.time() - 100.0      # rank 1 stopped publishing
    merged = aggregate.merge_snapshots(snaps)
    text = export.to_prometheus(merged)
    export.validate_prometheus(text)
    # per-rank series survive as postmortem signal...
    assert 'st_events_total{rank="0"} 1' in text
    assert 'st_events_total{rank="1"} 2' in text
    # ...but the cluster sum and bucket merge cover live ranks only
    assert "\nst_events_total 1\n" in "\n" + text
    assert "st_lat_seconds_count 1" in text
    assert "horovod_tpu_cluster_ranks_reporting 1" in text
    assert "horovod_tpu_cluster_ranks_stale 1" in text
    assert ('horovod_tpu_rank_snapshot_age_seconds'
            '{rank="0",stale="false"}') in text
    assert ('horovod_tpu_rank_snapshot_age_seconds'
            '{rank="1",stale="true"}') in text
    # both fresh: everything sums, nothing stale
    snaps[1]["time"] = _time.time()
    text = export.to_prometheus(aggregate.merge_snapshots(snaps))
    assert "\nst_events_total 3\n" in "\n" + text
    assert "horovod_tpu_cluster_ranks_reporting 2" in text
    assert "horovod_tpu_cluster_ranks_stale 0" in text


@pytest.mark.integration
@pytest.mark.slow
def test_flightrec_dump_on_np2_stall(tmp_path):
    """Acceptance: an induced np=2 stall auto-dumps a postmortem bundle
    whose attribution names the withholding rank (list + bitmap).
    slow-marked: the bundle/attribution logic is unit-tested above and
    the stall plumbing is covered by the np=4 straggler e2e; this job
    exists to prove the end-to-end auto-dump and costs two runner
    startups plus the full stall-shutdown wait."""
    res = _hvdrun(2, extra_env={
        "HVDTPU_TEST_MODE": "flightrec",
        "HVDTPU_FLIGHT_RECORDER_DIR": str(tmp_path),
        "HVDTPU_STALL_CHECK_TIME_SECONDS": "2",
        "HVDTPU_STALL_SHUTDOWN_TIME_SECONDS": "4",
    })
    assert res.returncode == 0, res.stdout + res.stderr
    assert "rank 0: FLIGHTREC-OK" in res.stdout, res.stdout
    assert "rank 1: FLIGHTREC-BYSTANDER-OK" in res.stdout, res.stdout
    assert list(tmp_path.glob("flightrec-rank0-*-stall_shutdown-*.json"))


def test_serving_request_metrics_reach_registry():
    import jax

    from horovod_tpu import serving
    from horovod_tpu.models import llama

    ttft = REGISTRY.get("hvd_serving_ttft_seconds")
    reqs = REGISTRY.get("hvd_serving_requests_total")
    before_count = ttft.count
    before_done = reqs.labels(outcome="finished").value

    cfg = llama.LlamaConfig.tiny()            # v256 d64 L2 H4 KV2 fp32
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    sess = serving.serve(params, cfg, num_blocks=16, block_size=8,
                         max_active=2)
    fut = sess.submit(np.arange(5, dtype=np.int32), max_tokens=4)
    sess.drain()
    res = fut.result(timeout=30)
    assert len(res.tokens) == 4
    assert ttft.count == before_count + 1
    assert reqs.labels(outcome="finished").value == before_done + 1
    assert REGISTRY.get("hvd_serving_kv_utilization") is not None
    text = hvd.metrics("prometheus")
    assert "hvd_serving_ttft_seconds_bucket" in text
    assert "hvd_serving_kv_utilization" in text
