"""DistributedOptimizer semantics.

Mirrors † ``test/parallel/test_torch.py`` ``test_gradient_aggregation`` /
``test_horovod_allreduce_grad`` and † TF ``gradient_aggregation`` tests:
averaged gradients equal the mean of per-rank gradients; aggregation fires
the collective every N-th call; compression round-trips.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from horovod_tpu.jaxcompat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops.compression import Compression

N = 8


def _mapped_update(tx, grads_per_rank, params):
    """Run tx.update inside shard_map over the hvd axis, one grad per rank."""
    mesh = hvd.mesh()
    opt_state = tx.init(params)

    def step(g, p):
        local = jax.tree.map(lambda a: a[0], g)   # strip rank dim
        updates, _ = tx.update(local, opt_state, p)
        return jax.tree.map(lambda u: u[None], updates)

    fn = shard_map(step, mesh=mesh, in_specs=(P("hvd"), P()),
                   out_specs=P("hvd"), check_vma=False)
    out = jax.jit(fn)(grads_per_rank, params)
    return out


def test_update_averages_across_ranks():
    params = {"w": jnp.zeros((4,), jnp.float32)}
    tx = hvd.DistributedOptimizer(optax.sgd(1.0))
    grads = hvd.per_rank([np.full((4,), float(r), np.float32)
                          for r in range(N)])
    updates = _mapped_update(tx, {"w": grads}, params)["w"]
    # SGD lr=1: update = -mean(grads) = -3.5, identical on every rank.
    got = hvd.to_numpy(updates)
    np.testing.assert_allclose(got, np.full((N, 4), -3.5), rtol=1e-6)


def test_update_sum_op():
    params = {"w": jnp.zeros((2,), jnp.float32)}
    tx = hvd.DistributedOptimizer(optax.sgd(1.0), op=hvd.Sum)
    grads = hvd.per_rank([np.full((2,), 1.0, np.float32)] * N)
    updates = _mapped_update(tx, {"w": grads}, params)["w"]
    np.testing.assert_allclose(hvd.to_numpy(updates), np.full((N, 2), -8.0),
                               rtol=1e-6)


def test_fp16_compression_roundtrip():
    params = {"w": jnp.zeros((3,), jnp.float32)}
    tx = hvd.DistributedOptimizer(optax.sgd(1.0),
                                  compression=Compression.fp16)
    grads = hvd.per_rank([np.full((3,), float(r), np.float32)
                          for r in range(N)])
    updates = _mapped_update(tx, {"w": grads}, params)["w"]
    got = hvd.to_numpy(updates)
    assert got.dtype == np.float32          # decompressed back
    np.testing.assert_allclose(got, np.full((N, 3), -3.5), rtol=1e-2)


def test_update_decomposed_schedule_parity():
    """sched_mode=decomposed routes the in-step gradient allreduce
    through ops.sched.overlap_allreduce; fp32 updates must be
    bit-identical to the monolithic psum path."""
    state = hvd.global_state()
    cfg = state.config
    params = {"w": jnp.zeros((3000,), jnp.float32)}
    grads = hvd.per_rank(
        [np.random.RandomState(r).randn(3000).astype(np.float32)
         for r in range(N)])
    tx = hvd.DistributedOptimizer(optax.sgd(1.0))
    base = hvd.to_numpy(_mapped_update(tx, {"w": grads}, params)["w"])
    old = (cfg.sched_mode, cfg.sched_chunks)
    cfg.sched_mode, cfg.sched_chunks = "decomposed", 3
    try:
        got = hvd.to_numpy(_mapped_update(tx, {"w": grads}, params)["w"])
    finally:
        cfg.sched_mode, cfg.sched_chunks = old
    assert np.array_equal(got, base)


def test_update_compiled_schedule_single_program():
    """sched_mode=compiled: the optax train step is ONE jitted program —
    updates bit-identical to the monolithic psum path AND the engine's
    per-chunk schedule dispatch counter never moves (inside jit the
    whole step already is one executable; this is the invariant the CI
    compiled-parity job's zero-dispatch guard pins at np=2/4)."""
    from horovod_tpu.ops.sched.executor import _m_sched
    cfg = hvd.global_state().config
    params = {"w": jnp.zeros((3000,), jnp.float32)}
    grads = hvd.per_rank(
        [np.random.RandomState(40 + r).randn(3000).astype(np.float32)
         for r in range(N)])
    tx = hvd.DistributedOptimizer(optax.sgd(1.0))
    base = hvd.to_numpy(_mapped_update(tx, {"w": grads}, params)["w"])
    old = (cfg.sched_mode, cfg.sched_chunks)
    before = _m_sched.total()
    cfg.sched_mode, cfg.sched_chunks = "compiled", 3
    try:
        got = hvd.to_numpy(_mapped_update(tx, {"w": grads}, params)["w"])
    finally:
        cfg.sched_mode, cfg.sched_chunks = old
    assert np.array_equal(got, base)
    assert _m_sched.total() == before


def test_update_decomposed_quant_within_bound():
    """Decomposed + int8 wire: the update stays inside the documented
    shared-scale quantization bound of the exact mean (the decomposed
    form re-quantizes the combined shard before the allgather, so it is
    close to — not bit-equal to — the monolithic quant path)."""
    state = hvd.global_state()
    cfg = state.config
    old = (cfg.sched_mode, cfg.sched_chunks, cfg.quant_min_bytes)
    g_np = np.stack([np.random.RandomState(100 + r).randn(4096)
                     .astype(np.float32) for r in range(N)])
    params = {"w": jnp.zeros((4096,), jnp.float32)}
    tx = hvd.DistributedOptimizer(optax.sgd(1.0),
                                  compression=Compression.int8)
    cfg.sched_mode, cfg.sched_chunks = "decomposed", 2
    cfg.quant_min_bytes = 1024
    try:
        got = hvd.to_numpy(
            _mapped_update(tx, {"w": hvd.per_rank(list(g_np))},
                           params)["w"])
    finally:
        (cfg.sched_mode, cfg.sched_chunks,
         cfg.quant_min_bytes) = old
    exact = -g_np.mean(0)                       # sgd lr=1 update
    gmax = np.abs(g_np).max()
    assert np.abs(got - exact).max() <= 1.5 * (N + 1) * gmax / 254.0


def test_backward_passes_per_step_accumulates():
    # With N_agg=3: two zero-update calls, then one averaged step.
    n_agg = 3
    params = {"w": jnp.zeros((2,), jnp.float32)}
    inner = optax.sgd(1.0)
    tx = hvd.DistributedOptimizer(inner, backward_passes_per_step=n_agg)
    mesh = hvd.mesh()

    def roll(g_seq, p):
        state = tx.init(p)
        outs = []
        for g in g_seq:
            updates, state = tx.update(g, state, p)
            outs.append(updates["w"])
        return jnp.stack(outs)

    def step(gs, p):
        g_seq = [{"w": gs[0, i]} for i in range(gs.shape[1])]
        return roll(g_seq, p)[None]

    grads = hvd.per_rank([
        np.stack([np.full((2,), float(r + 1 + 10 * i), np.float32)
                  for i in range(n_agg)]) for r in range(N)])
    fn = shard_map(step, mesh=mesh, in_specs=(P("hvd"), P()),
                   out_specs=P("hvd"), check_vma=False)
    outs = hvd.to_numpy(jax.jit(fn)(grads, params))  # [N, n_agg, 2]
    # First two updates are zero (accumulating).
    np.testing.assert_allclose(outs[:, 0], 0.0)
    np.testing.assert_allclose(outs[:, 1], 0.0)
    # Third: -mean over ranks of mean over micro-batches.
    per_rank_mean = np.stack([
        np.full((2,), np.mean([r + 1 + 10 * i for i in range(n_agg)]))
        for r in range(N)])
    expected = -per_rank_mean.mean(0)
    np.testing.assert_allclose(outs[:, 2], np.tile(expected, (N, 1)),
                               rtol=1e-5)


def test_distributed_gradients_eager():
    grads = {
        "a": hvd.per_rank([np.full((3,), float(r), np.float32)
                           for r in range(N)]),
        "b": hvd.per_rank([np.full((2, 2), float(2 * r), np.float32)
                           for r in range(N)]),
    }
    out = hvd.distributed_gradients(grads)
    np.testing.assert_allclose(hvd.to_numpy(out["a"]), np.full((3,), 3.5))
    np.testing.assert_allclose(hvd.to_numpy(out["b"]), np.full((2, 2), 7.0))


def test_bad_backward_passes():
    with pytest.raises(ValueError):
        hvd.DistributedOptimizer(optax.sgd(1.0), backward_passes_per_step=0)
