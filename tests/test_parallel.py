"""Parallelism modules: mesh building, ring/Ulysses attention, pipeline, MoE.

No reference analogue (Horovod is DP-only, SURVEY §2.6); correctness oracles
are the dense single-device computations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel import MeshConfig, build_mesh
from horovod_tpu.parallel import sharding as shd
from horovod_tpu.parallel.moe import (
    moe_layer,
    moe_layer_hvd,
    moe_layer_local,
    switch_route,
)
from horovod_tpu.parallel.pipeline import pipeline_apply
from horovod_tpu.parallel.ring_attention import (
    ring_self_attention,
    ulysses_attention_local,
)


def _dense_attention(q, k, v, causal):
    D = q.shape[-1]
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    if causal:
        Ls = q.shape[1]
        mask = np.tril(np.ones((Ls, Ls), bool))
        s = np.where(mask[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


# ---------------------------------------------------------------------------
# mesh
# ---------------------------------------------------------------------------

def test_mesh_config_auto():
    cfg = MeshConfig.auto(8)
    assert cfg.total == 8
    assert cfg.tp > 1 and cfg.dp > 1       # exercises at least tp+dp
    cfg32 = MeshConfig.auto(32)
    assert cfg32.total == 32


def test_build_mesh_axes():
    cfg = MeshConfig(dp=2, tp=2, sp=2)
    mesh = build_mesh(cfg)
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 2
    assert mesh.shape["pp"] == 1


def test_build_mesh_wrong_count():
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(dp=3))


def test_logical_sharding_rules():
    mesh = build_mesh(MeshConfig(dp=2, tp=2, sp=2))
    s = shd.logical_sharding(mesh, ("batch", "seq", "mlp"))
    assert s.spec == P(("dp", "fsdp"), "sp", "tp")
    with pytest.raises(KeyError):
        shd.spec_for(("nonexistent",))


# ---------------------------------------------------------------------------
# ring attention
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    B, S, H, D = 2, 32, 4, 8
    rng = np.random.RandomState(0)
    q = rng.randn(B, S, H, D).astype(np.float32)
    k = rng.randn(B, S, H, D).astype(np.float32)
    v = rng.randn(B, S, H, D).astype(np.float32)
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    sh = NamedSharding(mesh, P(None, "sp"))
    out = ring_self_attention(
        jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh),
        mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), _dense_attention(q, k, v, causal),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_grad_finite():
    B, S, H, D = 1, 16, 2, 4
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    sh = NamedSharding(mesh, P(None, "sp"))
    rng = np.random.RandomState(1)
    q = jax.device_put(rng.randn(B, S, H, D).astype(np.float32), sh)

    def loss(q_):
        o = ring_self_attention(q_, q_, q_, mesh, causal=True)
        return jnp.sum(o * o)

    g = jax.grad(loss)(q)
    assert np.isfinite(np.asarray(g)).all()


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_dense(causal):
    B, S, H, D = 2, 32, 8, 4   # H=8 divisible by sp=8
    rng = np.random.RandomState(2)
    q = rng.randn(B, S, H, D).astype(np.float32)
    k = rng.randn(B, S, H, D).astype(np.float32)
    v = rng.randn(B, S, H, D).astype(np.float32)
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    sh = NamedSharding(mesh, P(None, "sp"))
    from functools import partial
    from horovod_tpu.jaxcompat import shard_map
    fn = jax.jit(shard_map(
        partial(ulysses_attention_local, causal=causal),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False))
    out = fn(jax.device_put(q, sh), jax.device_put(k, sh),
             jax.device_put(v, sh))
    np.testing.assert_allclose(np.asarray(out), _dense_attention(q, k, v, causal),
                               rtol=2e-4, atol=2e-5)


# ---------------------------------------------------------------------------
# pipeline
# ---------------------------------------------------------------------------

def test_pipeline_matches_sequential():
    n_stage, M, mb, d = 8, 16, 4, 6
    rng = np.random.RandomState(3)
    # Stage s: x -> tanh(x @ W_s); stacked over stages.
    Ws = rng.randn(n_stage, d, d).astype(np.float32) * 0.3
    mesh = Mesh(np.array(jax.devices()), ("pp",))
    stacked = jax.device_put(Ws, NamedSharding(mesh, P("pp")))
    microbatches = rng.randn(M, mb, d).astype(np.float32)

    def stage_fn(W, x):
        return jnp.tanh(x @ W)

    out = pipeline_apply(stage_fn, stacked,
                         jax.device_put(microbatches,
                                        NamedSharding(mesh, P())),
                         mesh)
    # Sequential oracle.
    ref = microbatches.copy()
    for s in range(n_stage):
        ref = np.tanh(ref @ Ws[s])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=1e-5)


def test_pipeline_grad_flows():
    n_stage, M, mb, d = 8, 8, 2, 4
    rng = np.random.RandomState(4)
    Ws = rng.randn(n_stage, d, d).astype(np.float32) * 0.3
    mesh = Mesh(np.array(jax.devices()), ("pp",))
    mbs = jax.device_put(rng.randn(M, mb, d).astype(np.float32),
                         NamedSharding(mesh, P()))

    def loss(W):
        out = pipeline_apply(lambda w, x: jnp.tanh(x @ w),
                             W, mbs, mesh)
        return jnp.sum(out * out)

    g = jax.grad(loss)(jax.device_put(Ws, NamedSharding(mesh, P("pp"))))
    gn = np.asarray(g)
    assert np.isfinite(gn).all()
    assert (np.abs(gn) > 0).any(axis=(1, 2)).all(), \
        "every stage's params must receive gradient"


# ---------------------------------------------------------------------------
# MoE
# ---------------------------------------------------------------------------

def test_moe_layer_routes_and_combines():
    T, Dm, E = 64, 8, 8           # 8 experts over 8 devices
    rng = np.random.RandomState(5)
    tokens = rng.randn(T, Dm).astype(np.float32)
    router = rng.randn(Dm, E).astype(np.float32)
    # Expert e: x -> x @ We (per-expert matrix), stacked [E, Dm, Dm].
    We = rng.randn(E, Dm, Dm).astype(np.float32) * 0.5
    mesh = Mesh(np.array(jax.devices()), ("ep",))

    def expert_fn(w, x):
        return x @ w

    out, aux = moe_layer(
        jax.device_put(tokens, NamedSharding(mesh, P("ep"))),
        jax.device_put(router, NamedSharding(mesh, P())),
        expert_fn,
        jax.device_put(We, NamedSharding(mesh, P("ep"))),
        mesh, capacity_factor=8.0)   # ample capacity: nothing dropped
    out = np.asarray(out)
    aux = float(aux)

    # Oracle: top-1 routing with gate weighting, no drops.
    logits = tokens @ router
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    idx = p.argmax(-1)
    gate = p[np.arange(T), idx]
    expected = np.stack([gate[t] * (tokens[t] @ We[idx[t]])
                         for t in range(T)])
    np.testing.assert_allclose(out, expected, rtol=2e-3, atol=1e-4)
    assert aux > 0


def test_moe_capacity_drops_overflow():
    # Capacity factor so small most tokens drop: output for dropped tokens
    # must be exactly zero (residual recovers them in a real model).
    T, Dm, E = 64, 4, 8
    rng = np.random.RandomState(6)
    tokens = rng.randn(T, Dm).astype(np.float32)
    router = np.zeros((Dm, E), np.float32)  # uniform → all to expert 0
    We = np.stack([np.eye(Dm, dtype=np.float32)] * E)
    mesh = Mesh(np.array(jax.devices()), ("ep",))
    out, _ = moe_layer(
        jax.device_put(tokens, NamedSharding(mesh, P("ep"))),
        jax.device_put(router, NamedSharding(mesh, P())),
        lambda w, x: x @ w,
        jax.device_put(We, NamedSharding(mesh, P("ep"))),
        mesh, capacity_factor=0.25)
    out = np.asarray(out)
    zero_rows = (np.abs(out) < 1e-12).all(axis=1).sum()
    assert zero_rows > 0, "expected overflow drops with tiny capacity"


def test_switch_route_drop_mask_matches_overflow():
    # The explicit drop mask must name exactly the tokens past capacity:
    # dropped[t] <=> token t contributes nothing to dispatch/combine.
    T, E, C = 16, 4, 2
    logits = jnp.asarray(np.random.RandomState(3).randn(T, E), jnp.float32)
    dispatch, combine, _, dropped = switch_route(logits, C)
    kept_mass = np.asarray(dispatch).sum(axis=(1, 2))   # 1 kept, 0 dropped
    np.testing.assert_array_equal(np.asarray(dropped), kept_mass == 0.0)
    # Per-expert kept count never exceeds capacity.
    per_expert = np.asarray(dispatch).sum(axis=(0, 2))
    assert (per_expert <= C).all(), per_expert
    # The combine mass of dropped tokens is exactly zero.
    assert np.asarray(combine)[np.asarray(dropped)].sum() == 0.0


def test_moe_layer_counts_dropped_tokens():
    from horovod_tpu.obs import REGISTRY
    T, Dm, E = 64, 4, 8
    rng = np.random.RandomState(6)
    tokens = rng.randn(T, Dm).astype(np.float32)
    router = np.zeros((Dm, E), np.float32)  # uniform → all to expert 0
    We = np.stack([np.eye(Dm, dtype=np.float32)] * E)
    mesh = Mesh(np.array(jax.devices()), ("ep",))
    fam = REGISTRY.get("hvd_moe_dropped_tokens_total")
    before = fam.labels(layer="t_drop").value
    moe_layer(
        jax.device_put(tokens, NamedSharding(mesh, P("ep"))),
        jax.device_put(router, NamedSharding(mesh, P())),
        lambda w, x: x @ w,
        jax.device_put(We, NamedSharding(mesh, P("ep"))),
        mesh, capacity_factor=0.25, layer="t_drop")
    delta = fam.labels(layer="t_drop").value - before
    # All T tokens route to expert 0; its per-shard capacity is 1, so
    # every shard drops all but one of its tokens.
    assert delta == T - len(jax.devices()), delta


@pytest.mark.parametrize("ep", [1, 2, 4])
def test_moe_layer_parity_across_ep(ep):
    """moe_layer over ep ∈ {1,2,4} against the dense per-token oracle.

    Ample capacity (nothing drops — per-shard capacity changes with ep,
    so drop behavior is only comparable when it never engages).  fp32
    end to end; einsum dispatch vs direct matmul differ only in
    summation order, so 1e-5 bounds the drift."""
    T, Dm, E = 32, 8, 4
    rng = np.random.RandomState(11)
    tokens = rng.randn(T, Dm).astype(np.float32)
    router = rng.randn(Dm, E).astype(np.float32)
    We = rng.randn(E, Dm, Dm).astype(np.float32) * 0.5
    mesh = Mesh(np.array(jax.devices()[:ep]), ("ep",))
    out, aux = moe_layer(
        jax.device_put(tokens, NamedSharding(mesh, P("ep"))),
        jax.device_put(router, NamedSharding(mesh, P())),
        lambda w, x: x @ w,
        jax.device_put(We, NamedSharding(mesh, P("ep"))),
        mesh, capacity_factor=float(E))
    logits = tokens @ router
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    idx = p.argmax(-1)
    gate = p[np.arange(T), idx]
    expected = np.stack([gate[t] * (tokens[t] @ We[idx[t]])
                         for t in range(T)])
    np.testing.assert_allclose(np.asarray(out), expected,
                               rtol=1e-5, atol=1e-5)
    assert float(aux) > 0


def test_moe_layer_hvd_parity_with_drops():
    """The engine-verb path (`hvd.alltoall` dispatch/combine) against a
    per-rank dense oracle that replicates its capacity-drop rule: kept
    tokens match the oracle to fp32 tolerance, dropped tokens are
    exactly zero, and the total feeds the drop counter."""
    from horovod_tpu.obs import REGISTRY
    n = hvd.size()
    D, E, T, cf = 8, 16, 10, 1.25
    rng = np.random.RandomState(7)
    router = rng.randn(D, E).astype(np.float32)
    W = rng.randn(E, D, D).astype(np.float32) * 0.5
    toks = [rng.randn(T, D).astype(np.float32) for _ in range(n)]
    E_local = E // n
    params = [jnp.asarray(W[r * E_local:(r + 1) * E_local])
              for r in range(n)]
    fam = REGISTRY.get("hvd_moe_dropped_tokens_total")
    before = fam.labels(layer="t_hvd").value

    outs, aux, dropped = moe_layer_hvd(
        toks, router, lambda w, x: x @ w, params,
        capacity_factor=cf, layer="t_hvd")

    capacity = max(1, int(T * cf / E))
    oracle_drops = 0
    for r in range(n):
        logits = toks[r] @ router
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        idx = p.argmax(-1)
        gate = p[np.arange(T), idx]
        seen = {e: 0 for e in range(E)}
        for t in range(T):
            e = int(idx[t])
            if seen[e] < capacity:
                seen[e] += 1
                np.testing.assert_allclose(
                    np.asarray(outs[r][t]), gate[t] * (toks[r][t] @ W[e]),
                    rtol=1e-5, atol=1e-5)
            else:
                oracle_drops += 1
                np.testing.assert_array_equal(np.asarray(outs[r][t]), 0.0)
    assert dropped == oracle_drops and oracle_drops > 0
    assert fam.labels(layer="t_hvd").value - before == oracle_drops
    assert np.isfinite(aux) and aux > 0


def test_pipeline_1f1b_matches_autodiff_oracle():
    """1F1B schedule (pipeline_train_local): loss and every gradient must
    equal plain autodiff through the sequential stage composition."""
    from horovod_tpu.jaxcompat import shard_map
    from horovod_tpu.parallel.pipeline import pipeline_train_local

    n_stage, M, mb, d = 8, 8, 2, 4
    rng = np.random.RandomState(7)
    Ws = (rng.randn(n_stage, d, d) * 0.3).astype(np.float32)
    bias = rng.randn(d).astype(np.float32)
    mbs = rng.randn(M, mb, d).astype(np.float32)
    tgts = rng.randn(M, mb, d).astype(np.float32)
    mesh = Mesh(np.array(jax.devices()), ("pp",))

    def stage_fn(W, x):
        return jnp.tanh(x @ W), jnp.float32(0.0)

    def loss_head(hp, y, m):
        t = jnp.asarray(tgts)[m]
        return jnp.mean((y + hp - t) ** 2)

    def local(Wloc, hp, mb_in):
        W1 = Wloc[0]  # leading pp dim stripped to this stage's weight
        loss, aux, dmbs, dW, dhp = pipeline_train_local(
            stage_fn, W1, mb_in, loss_head, hp, axis_name="pp")
        return loss, dmbs, dW[None], dhp

    fn = jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(P("pp"), P(), P()),
        out_specs=(P(), P(), P("pp"), P()),
        check_vma=False))
    loss, dmbs, dW, dhp = fn(jnp.asarray(Ws), jnp.asarray(bias),
                             jnp.asarray(mbs))

    # Oracle: plain autodiff through the sequential composition.
    def oracle(Ws_, hp, mbs_):
        def one(m):
            x = mbs_[m]
            for s in range(n_stage):
                x = jnp.tanh(x @ Ws_[s])
            return jnp.mean((x + hp - jnp.asarray(tgts)[m]) ** 2)
        return sum(one(m) for m in range(M)) / M

    oloss, (odW, odhp, odmbs) = jax.value_and_grad(oracle, argnums=(0, 1, 2))(
        jnp.asarray(Ws), jnp.asarray(bias), jnp.asarray(mbs))
    np.testing.assert_allclose(float(loss), float(oloss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dW), np.asarray(odW),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dhp), np.asarray(odhp),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dmbs), np.asarray(odmbs),
                               rtol=1e-4, atol=1e-6)
