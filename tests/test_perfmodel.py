"""Perf-model math (obs/perfmodel) and the sampling profiler (obs/prof).

The model's stdlib wire accounting must agree with the engine's own
``ops.reduction.ring_wire_bytes`` (the perfmodel docstring's contract —
the duplication exists only because the obs plane imports without jax),
and the expected-cost walk must match the hand-derived ring formulas per
verb x wire mode x chunking, plus the hierarchical two-tier split.
"""

import threading
import time
import urllib.request

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.obs import REGISTRY, perfmodel, server
from horovod_tpu.obs.perfmodel import (
    PerfModel, busbw_factor, expected_allreduce, expected_collective,
    expected_hierarchical, wire_per_elem)
from horovod_tpu.obs.prof import SamplingProfiler

MODES = ("fp32", "bf16", "fp16", "int8", "fp8")


# -- wire accounting agrees with the engine's ----------------------------

@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("n", (2, 4, 8))
@pytest.mark.parametrize("nbytes", (4096, 1 << 20, 1 << 22))
def test_wire_bytes_agree_with_ring_wire_bytes(mode, n, nbytes):
    from horovod_tpu.ops import reduction as R
    block = 512
    cost = expected_allreduce(nbytes, n, mode=mode, block=block)
    want = R.ring_wire_bytes(mode, nbytes, n, block, itemsize=4)
    assert cost.wire_bytes == pytest.approx(want, rel=1e-9), (mode, n)


def test_wire_per_elem_widths():
    # fp32 moves each element twice at full width; casts at half; quant
    # at ~3 bytes + the per-block scale amortized.
    assert wire_per_elem("fp32") == 8.0
    assert wire_per_elem("bf16") == 4.0 and wire_per_elem("fp16") == 4.0
    assert wire_per_elem("int8", block=512) == 3.0 + 8.0 / 512
    assert wire_per_elem("fp8", block=128) == 3.0 + 8.0 / 128


# -- expected-cost walk, per verb / chunking / hierarchy -----------------

def test_expected_allreduce_monolithic_ring_math():
    cost = expected_allreduce(1 << 20, 8, mode="fp32")
    numel = (1 << 20) / 4
    assert cost.wire_bytes == pytest.approx((7 / 8) * 8.0 * numel)
    assert cost.steps == 2 * 7
    assert cost.schedule == "monolithic"
    assert cost.busbw_factor == pytest.approx(2 * 7 / 8)


@pytest.mark.parametrize("k", (2, 4, 8))
def test_chunking_multiplies_steps_not_wire(k):
    mono = expected_allreduce(1 << 20, 8, mode="int8", chunks=1)
    dec = expected_allreduce(1 << 20, 8, mode="int8", chunks=k)
    assert dec.wire_bytes == pytest.approx(mono.wire_bytes)
    assert dec.steps == mono.steps * k
    assert dec.schedule == f"rs_ag:{k}"


def test_expected_zero_step_fp32_matches_dense_wire():
    """The ZeRO-1 claim in wire terms: rs half of the gradient plus one
    raw parameter allgather sums to EXACTLY the dense allreduce wire for
    fp32 (param_bytes == payload_bytes), while the latency-step count
    only drops the gradient-allgather chunks."""
    from horovod_tpu.obs.perfmodel import expected_zero_step
    for n in (2, 4, 8):
        dense = expected_allreduce(1 << 20, n, mode="fp32", chunks=4)
        zero = expected_zero_step(1 << 20, n, mode="fp32", chunks=4)
        assert zero.wire_bytes == pytest.approx(dense.wire_bytes)
        assert zero.verb == "zero_step"
        assert zero.schedule == "zero1:rs_ag:4"
        assert zero.steps == (n - 1) * 4 + (n - 1)
        assert set(zero.tiers) == {"rs", "param_ag"}
        assert zero.tiers["rs"].wire_bytes \
            + zero.tiers["param_ag"].wire_bytes \
            == pytest.approx(zero.wire_bytes)


def test_expected_zero_step_quant_tiers_and_compiled():
    """Quant ZeRO: only the rs tier keeps the narrow wire width (half
    the quant allreduce per-element cost); the param allgather moves raw
    fp32 param bytes — dearer than dense's quantized allgather half, and
    the model shows that trade instead of hiding it.  Compiled collapses
    per-chunk dispatch steps to one ring."""
    from horovod_tpu.obs.perfmodel import expected_zero_step
    numel = (1 << 20) / 4
    frac = 7 / 8
    zero = expected_zero_step(1 << 20, 8, mode="int8", chunks=2,
                              block=512)
    assert zero.tiers["rs"].wire_bytes == pytest.approx(
        frac * (wire_per_elem("int8", block=512) / 2.0) * numel)
    assert zero.tiers["param_ag"].wire_bytes == pytest.approx(
        frac * (1 << 20))
    dense = expected_allreduce(1 << 20, 8, mode="int8", chunks=2,
                               block=512)
    assert zero.tiers["rs"].wire_bytes < dense.wire_bytes
    assert zero.wire_bytes > dense.wire_bytes   # the exactness premium
    compiled = expected_zero_step(1 << 20, 8, mode="int8", chunks=2,
                                  compiled=True)
    assert compiled.steps == 2 * 7
    assert compiled.schedule == "zero1:compiled:rs_ag:2"
    assert compiled.wire_bytes == pytest.approx(zero.wire_bytes)


@pytest.mark.parametrize("verb", ("allgather", "alltoall",
                                  "reducescatter", "broadcast"))
def test_single_phase_verbs(verb):
    cost = expected_collective(verb, 1 << 20, 4)
    assert cost.wire_bytes == pytest.approx((3 / 4) * (1 << 20))
    assert cost.steps == 3
    assert cost.busbw_factor == pytest.approx(3 / 4)


def test_single_rank_has_no_wire():
    assert busbw_factor("allreduce", 1) == 0.0
    cost = expected_allreduce(1 << 20, 1)
    assert cost.wire_bytes == 0.0 and cost.steps == 0
    # ...and the model refuses to score it (nothing to attribute).
    assert PerfModel().record(cost, 1.0) is None


def test_hierarchical_two_tier_split():
    B = 1 << 22
    cost = expected_hierarchical(B, n_local=4, n_cross=2)
    local, cross = cost.tiers["local"], cost.tiers["cross"]
    # Local: rs + ag of the full payload over 4; cross: full allreduce
    # of the 1/4 shard over 2.
    assert local.wire_bytes == pytest.approx(2 * (3 / 4) * B)
    assert cross.wire_bytes == pytest.approx(2 * (1 / 2) * (B / 4))
    assert local.steps == 2 * 3 and cross.steps == 2 * 1
    assert cost.wire_bytes == pytest.approx(
        local.wire_bytes + cross.wire_bytes)
    assert cost.n == 8 and cost.schedule == "hier"


def test_hierarchical_per_tier_modes_and_chunks():
    """Each tier rides its own wire mode; chunking multiplies per-tier
    steps, never wire bytes; mixed modes show up in the labels."""
    B = 1 << 22
    base = expected_hierarchical(B, 4, 2)
    mixed = expected_hierarchical(B, 4, 2, mode="fp32", cross_mode="int8",
                                  chunks=2)
    # int8 DCN hop shrinks ONLY the cross tier's bytes.
    assert mixed.tiers["local"].wire_bytes == pytest.approx(
        base.tiers["local"].wire_bytes)
    w_int8 = wire_per_elem("int8", 4, 512) / 8.0
    assert mixed.tiers["cross"].wire_bytes == pytest.approx(
        base.tiers["cross"].wire_bytes * w_int8)
    # Chunked tiered schedule label + per-tier step multiplication.
    assert mixed.schedule == "hier:4:2"
    assert mixed.mode == "fp32/int8"
    assert mixed.tiers["local"].steps == base.tiers["local"].steps * 2
    assert mixed.tiers["cross"].steps == base.tiers["cross"].steps * 2
    # Same mode on both tiers keeps the plain label.
    both = expected_hierarchical(B, 4, 2, mode="int8", cross_mode="int8")
    assert both.mode == "int8" and both.schedule == "hier"


def test_hier_split_table_flips_flat_to_hier():
    """Small messages pay 3x the phase-dispatch overhead and stay flat;
    large messages win on the 1/n_local cross-tier volume."""
    rows = perfmodel.hier_split_table(
        [1 << 10, 1 << 16, 1 << 20, 1 << 26], 8, 4,
        gbs_local=10.0, gbs_cross=1.0)
    by_size = {r["payload_bytes"]: r["split"] for r in rows}
    assert by_size[1 << 10] == "flat"
    assert by_size[1 << 26] == "hier"
    # Monotone once it flips: no hier->flat->hier zigzag.
    splits = [r["split"] for r in rows]
    assert splits == sorted(splits, key=lambda s: s == "hier")
    for r in rows:
        assert r["flat_seconds"] > 0 and r["hier_seconds"] > 0
    with pytest.raises(ValueError):
        perfmodel.hier_split_table([1 << 20], 8, 3, gbs_local=10.0,
                                   gbs_cross=1.0)


def test_observe_tiers_extended_keywords():
    """The chunked+tiered executor feeds schedule/mode/chunks through
    observe_tiers; the recorded cost carries the descriptor label."""
    m = PerfModel()
    m.configure(link_gbs=1.0, link_latency_us=0.0)
    out = m.observe_tiers(
        1 << 22, 4, 2, 0.1, tier_seconds={"local": 0.08, "cross": 0.02},
        mode="fp32", cross_mode="int8", chunks=2, schedule="hier:4:2")
    assert out is not None
    fam = REGISTRY.get("hvd_perf_efficiency")
    labels = [s["labels"] for s in fam._samples()]
    assert any(lb.get("schedule") == "hier:4:2"
               and lb.get("mode") == "fp32/int8" for lb in labels), labels


# -- efficiency scoring --------------------------------------------------

def test_peak_basis_self_calibrates():
    m = PerfModel()
    cost = expected_allreduce(1 << 20, 8)
    first = m.record(cost, 0.010)
    assert first["basis"] == "peak" and first["efficiency"] == 1.0
    slower = m.record(cost, 0.020)
    assert slower["efficiency"] == pytest.approx(0.5)
    faster = m.record(cost, 0.005)    # new peak resets the denominator
    assert faster["efficiency"] == 1.0


def test_link_basis_scores_against_configured_model():
    m = PerfModel()
    m.configure(link_gbs=1.0, link_latency_us=0.0)
    cost = expected_allreduce(1 << 20, 8, mode="fp32")
    exp_s = cost.expected_seconds(1.0, 0.0)
    row = m.record(cost, exp_s * 2)
    assert row["basis"] == "link"
    assert row["efficiency"] == pytest.approx(0.5)
    assert m.record(cost, exp_s)["efficiency"] == pytest.approx(1.0)


def test_observe_schedule_union_span_and_imbalance():
    m = PerfModel()
    row = m.observe_schedule(
        descriptor="rs_ag:2", mode="fp32", payload_bytes=1 << 20, n=4,
        chunks=2, comm_windows=[(0.0, 0.010), (0.012, 0.040)],
        compute_windows=[(0.010, 0.012)])
    assert row["schedule"] == "rs_ag:2"
    assert row["seconds"] == pytest.approx(0.040)   # union of all spans
    imb = REGISTRY.get("hvd_perf_chunk_imbalance")
    # slowest chunk 28ms vs mean 19ms
    assert imb.value == pytest.approx(0.028 / 0.019, rel=1e-6)


def test_observe_tiers_attribution():
    m = PerfModel()
    m.configure(link_gbs=1.0, link_latency_us=0.0)
    out = m.observe_tiers(1 << 22, 4, 2, seconds=0.1,
                          tier_seconds={"local": 0.08, "cross": 0.02})
    # Expected fractions follow the wire split: local 6/7, cross 1/7.
    assert out["local"]["expected_fraction"] == pytest.approx(6 / 7)
    assert out["cross"]["expected_fraction"] == pytest.approx(1 / 7)
    exp_local = (2 * (3 / 4) * (1 << 22)) / 1e9
    assert out["local"]["excess_seconds"] == pytest.approx(
        0.08 - exp_local, rel=1e-6)


def test_observe_never_raises_and_exports_gauges():
    m = PerfModel()
    assert m.observe("allreduce", -5, "bogus", None) is None
    row = m.observe("alltoall", 1 << 16, 4, 0.001)
    assert row is not None
    fam = REGISTRY.get("hvd_perf_efficiency")
    labels = [s["labels"] for s in fam._samples()]
    assert any(lb.get("verb") == "alltoall" for lb in labels), labels


# -- sampling profiler ---------------------------------------------------

def test_profiler_samples_busy_thread_and_bounds_table():
    prof = SamplingProfiler(hz=200.0, max_stacks=64, ring=16)
    stop = threading.Event()

    def _spin_hot_loop():
        while not stop.is_set():
            sum(range(200))

    t = threading.Thread(target=_spin_hot_loop, name="hotspot",
                         daemon=True)
    t.start()
    try:
        assert prof.start()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            hot = prof.hot_stacks(limit=50)
            if any(r["thread"] == "hotspot" and
                   any("_spin_hot_loop" in fr for fr in r["stack"])
                   for r in hot):
                break
            time.sleep(0.02)
        else:
            raise AssertionError(f"busy thread never sampled: {hot}")
    finally:
        prof.stop()
        stop.set()
        t.join()
    assert not prof.running
    snap = prof.snapshot()
    assert snap["samples"] > 0 and not snap["enabled"]
    assert len(snap["hot_stacks"]) <= 64
    fs = prof.flight_summary()
    assert len(fs["ring"]) <= 16 and fs["hot_stacks"]


def test_profz_routes_on_obs_server():
    hvd.init()
    srv = server.MetricsServer(0, addr="127.0.0.1")
    try:
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/profz", timeout=10
        ).read().decode()
        assert "sampling profiler" in text and "hot stacks" in text
        import json
        snap = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/profz.json", timeout=10
        ).read().decode())
        assert {"enabled", "hz", "hot_stacks", "engine_phases"} <= \
            set(snap)
    finally:
        srv.close()


def test_flightrec_bundle_carries_profiler_ring(tmp_path):
    from horovod_tpu.obs import flightrec
    from horovod_tpu.obs.prof import PROFILER
    PROFILER.configure(hz=100.0)
    was = PROFILER.running
    PROFILER.start()
    try:
        time.sleep(0.1)
        path = flightrec.RECORDER.dump(str(tmp_path / "bundle.json"),
                                       reason="test")
    finally:
        if not was:
            PROFILER.stop()
    import json
    with open(path) as fh:
        bundle = json.load(fh)
    prof = bundle["profile"]
    assert prof["enabled"] and prof["hz"] == 100.0
    assert prof["ring"], "recent stack ring missing from bundle"
    assert all({"t", "threads"} <= set(e) for e in prof["ring"])


def test_perf_gauges_reach_metrics_endpoint_after_collective():
    """Single-process rig: one allreduce through the engine must land
    hvd_perf_efficiency{verb=allreduce,schedule=monolithic} on /metrics
    (the np=2 /cluster half lives in mp_obs_worker).  The async verb is
    the engine dispatch path the model instruments; the sync wrapper is
    a pure in-jit collective with no host-side dispatch to time."""
    hvd.init()
    n = hvd.size()
    if n <= 1:
        pytest.skip("needs a multi-device rig")
    h = hvd.allreduce_async(
        hvd.per_rank([np.ones((1024,), np.float32) for _ in range(n)]),
        hvd.Sum, name="perf_gauge_probe")
    out = hvd.synchronize(h)
    assert float(np.ravel(hvd.to_numpy(out))[0]) == float(n)
    text = hvd.metrics("prometheus")
    assert ('hvd_perf_efficiency{mode="fp32",schedule="monolithic",'
            'tier="flat",verb="allreduce"}') in text, text
