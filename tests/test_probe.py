"""NIC discovery + connectivity probe stage († driver_service probe round,
task_fn NIC registration).

The probe protocol runs for real here — two probe tasks as genuine
subprocesses against a live KV store — with "hosts" standing on
localhost (the reference tests its driver service the same way: the
protocol is pure TCP, host placement is ssh's job).
"""

import os
import subprocess
import sys

import pytest

from horovod_tpu._native import KvClient, KvServer
from horovod_tpu.runner.probe import (
    local_addresses,
    run_probe_stage,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_local_addresses_nonempty_loopback_last():
    addrs = local_addresses()
    assert addrs, "no NIC addresses discovered"
    if len(addrs) > 1:
        assert not addrs[0].startswith("127."), addrs


def _probe_proc(host_key: str, kv_port: int) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.probe",
         host_key, "127.0.0.1", str(kv_port)],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)


def test_probe_stage_end_to_end():
    with KvServer() as srv:
        kv = KvClient("127.0.0.1", srv.port)
        result = run_probe_stage(
            ["hostA", "hostB"],
            kv=kv,
            launch_fn=lambda h: _probe_proc(h, srv.port),
            timeout=30.0)
        kv.close()
    # Both hosts are this machine: the agreed driver address is the one
    # candidate offered, and each host was reached by its peer.
    assert result["driver_addr"] == "127.0.0.1"
    assert set(result["host_addrs"]) == {"hostA", "hostB"}
    assert set(result["nics"]) == {"hostA", "hostB"}
    for addrs in result["nics"].values():
        assert addrs


def test_probe_stage_reports_unregistered_host():
    # Both probes are dead-on-arrival processes: no import-speed race, and
    # the stage must name the first host that never registered.
    with KvServer() as srv:
        kv = KvClient("127.0.0.1", srv.port)

        def launch_fn(h):
            return subprocess.Popen(
                [sys.executable, "-c", "import sys; sys.exit(3)"])

        with pytest.raises(RuntimeError, match="hostBAD"):
            run_probe_stage(["hostBAD", "hostB"], kv=kv,
                            launch_fn=launch_fn, timeout=5.0)
        kv.close()


def test_probe_task_driver_unreachable():
    # No KV server on this port: the task must fail fast with rc=3.
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.probe",
         "hostX", "127.0.0.1", "1"],  # port 1: nothing listens
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    out, err = proc.communicate(timeout=30)
    assert proc.returncode == 3, (proc.returncode, err)
    assert "driver unreachable" in err
