"""Reduction algebra: quantization numerics, wire accounting, resolution.

The quantized-allreduce error model under test (ops/reduction.py): with
shared per-block scales ``s = gmax/qmax``, each rank's contribution
quantizes with error <= s/2, the narrow-container sums are EXACT, and the
allgather re-quantization adds one more s'/2 — so an n-rank SUM is off by
at most ``(n + n) * gmax / (2*qmax)`` per element (reduce-scatter n
contributions + requant of an n-scaled result), and an AVERAGE by
``2 * gmax / (2*qmax)``.  Tests assert these bounds with a 1.5x safety
margin (fp32 arithmetic inside the kernel adds ulps, not halves).
"""

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.ops import collectives as C
from horovod_tpu.ops import reduction as R

N = 8


@pytest.fixture(autouse=True)
def _no_size_floor():
    cfg = hvd.global_state().config
    old_floor, old_block, old_mode = (
        cfg.quant_min_bytes, cfg.quant_block_size, cfg.wire_precision)
    cfg.quant_min_bytes = 0
    yield
    cfg.quant_min_bytes = old_floor
    cfg.quant_block_size = old_block
    cfg.wire_precision = old_mode


# ---------------------------------------------------------------------------
# encode/decode round trip: per-block error bounds
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("block", [64, 256, 512])
@pytest.mark.parametrize("mode,qmax", [("int8", 127.0), ("fp8", 448.0)])
def test_roundtrip_error_bound_per_block(mode, qmax, block):
    import jax.numpy as jnp
    alg = R.algebra_for(mode)
    rng = np.random.RandomState(7)
    x = (rng.randn(12, block) * 10 ** rng.uniform(-3, 3, (12, 1))
         ).astype(np.float32)
    wire, scales = alg.wire_encode(jnp.asarray(x))
    back = np.asarray(alg.wire_decode(wire, scales))
    amax = np.abs(x).max(axis=-1, keepdims=True)
    if mode == "int8":
        bound = amax / (2 * qmax) * 1.001      # half a quantization step
    else:
        # e4m3: 3 mantissa bits -> rel err <= 2^-4 of the value, but
        # bound per block by the scale-normalized worst case.
        bound = amax * 2.0 ** -4 * 1.001
    assert (np.abs(back - x) <= bound + 1e-12).all(), mode


def test_roundtrip_zero_block_finite():
    import jax.numpy as jnp
    for mode in ("int8", "fp8"):
        alg = R.algebra_for(mode)
        x = jnp.zeros((2, 64), jnp.float32)
        wire, scales = alg.wire_encode(x)
        back = np.asarray(alg.wire_decode(wire, scales))
        assert np.isfinite(back).all() and (back == 0).all()


# ---------------------------------------------------------------------------
# allreduce parity vs fp32, both engine-visible paths
# ---------------------------------------------------------------------------

def _parity_case(mode, op, block=512, numel=5000, seed=0):
    cfg = hvd.global_state().config
    cfg.quant_block_size = block
    rng = np.random.RandomState(seed)
    parts = [rng.randn(numel).astype(np.float32) for _ in range(N)]
    x = hvd.per_rank(parts)
    exact = np.stack(parts).sum(0)
    if op is hvd.Average:
        exact = exact / N
    got = hvd.to_numpy(C.allreduce(x, op, precision=mode))
    gmax = max(np.abs(p).max() for p in parts)
    scale_sum = N if op is hvd.Sum else 1.0
    if mode == "int8":
        atol = 1.5 * (N + scale_sum) * gmax / 254.0
    elif mode == "fp8":
        atol = 1.5 * (N + scale_sum) * gmax / 16.0
    else:  # bf16/fp16 cast wire: 8-bit / 11-bit mantissa sums
        atol = (N + scale_sum) * gmax * (2.0 ** -7)
    np.testing.assert_allclose(got, exact, atol=atol)
    return np.abs(got - exact).max(), atol


@pytest.mark.parametrize("mode", ["bf16", "fp16", "int8", "fp8"])
@pytest.mark.parametrize("op", [hvd.Sum, hvd.Average])
def test_allreduce_parity_within_tolerance(mode, op):
    err, atol = _parity_case(mode, op)
    assert err > 0 or mode in ("bf16", "fp16")  # quantization is lossy


@pytest.mark.parametrize("block", [64, 512])
def test_allreduce_parity_across_block_sizes(block):
    _parity_case("int8", hvd.Average, block=block, numel=3000, seed=3)


def test_allreduce_unaligned_sizes_pad_correctly():
    # numel not divisible by n*block exercises the pad/unpad path.
    for numel in (1, 7, 513, 4097):
        _parity_case("int8", hvd.Sum, numel=numel, seed=numel)


def test_grouped_allreduce_quantized_parity():
    rng = np.random.RandomState(1)
    groups = [[rng.randn(130).astype(np.float32) for _ in range(N)]
              for _ in range(4)]
    outs = C.grouped_allreduce(
        [hvd.per_rank(p) for p in groups], hvd.Average, precision="int8")
    for parts, out in zip(groups, outs):
        exact = np.stack(parts).mean(0)
        gmax = np.abs(np.stack(parts)).max()
        np.testing.assert_allclose(hvd.to_numpy(out), exact,
                                   atol=1.5 * (N + 1) * gmax / 254.0)


def test_engine_async_fused_quantized_parity():
    handles, exacts, gmaxes = [], [], []
    rng = np.random.RandomState(2)
    for i in range(6):
        parts = [rng.randn(257).astype(np.float32) for _ in range(N)]
        exacts.append(np.stack(parts).mean(0))
        gmaxes.append(np.abs(np.stack(parts)).max())
        handles.append(hvd.allreduce_async(
            hvd.per_rank(parts), hvd.Average, name=f"t.red.q{i}",
            compression="int8"))
    for h, exact, gmax in zip(handles, exacts, gmaxes):
        got = hvd.to_numpy(hvd.synchronize(h))
        np.testing.assert_allclose(got, exact,
                                   atol=1.5 * (N + 1) * gmax / 254.0)


def test_zero_block_rank_does_not_poison_shared_scale():
    """Regression (review finding): a rank whose block is all zeros
    (frozen layer, sparse gradient, or a joined rank's fabricated zero
    payload) must not drag the mesh-agreed scale to the 1.0 sentinel —
    the pmax runs over RAW absmax, so small real magnitudes on the other
    ranks survive quantization."""
    cfg = hvd.global_state().config
    cfg.quant_block_size = 512
    small = 0.01
    parts = [np.zeros(1024, np.float32)] + \
        [np.full(1024, small, np.float32) for _ in range(N - 1)]
    exact = np.stack(parts).mean(0)
    for mode, qmax in (("int8", 127.0), ("fp8", 448.0)):
        got = hvd.to_numpy(C.allreduce(hvd.per_rank(parts), hvd.Average,
                                       precision=mode))
        # Pre-fix this returned exactly 0 (error == exact); post-fix the
        # error is bounded by the documented shared-scale model.
        atol = 1.5 * (N + 1) * small / (2 * qmax)
        np.testing.assert_allclose(got, exact, atol=atol)
        assert np.abs(got).max() > 0, mode


def test_in_context_zero_block_rank():
    import jax
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.jaxcompat import shard_map
    state = hvd.global_state()
    mesh, axis = state.mesh, state.config.dp_axis_name

    def kern(v):
        return R.in_context_allreduce(v[0], axis, "int8", average=True)[None]

    fn = jax.jit(shard_map(kern, mesh=mesh, in_specs=P(axis),
                           out_specs=P(axis), check_vma=False))
    parts = np.full((N, 512), 0.02, np.float32)
    parts[0] = 0.0
    out = np.asarray(fn(hvd.per_rank(list(parts))))
    exact = parts.mean(0)
    np.testing.assert_allclose(out[0], exact,
                               atol=1.5 * (N + 1) * 0.02 / 254.0)
    assert np.abs(out).max() > 0


def test_compression_namespace_routes_modes():
    assert R.as_wire_mode(hvd.Compression.int8) == "int8"
    assert R.as_wire_mode(hvd.Compression.fp8) == "fp8"
    assert R.as_wire_mode(hvd.Compression.fp16) == "bf16"
    assert R.as_wire_mode(hvd.Compression.fp16_ieee) == "fp16"
    assert R.as_wire_mode(hvd.Compression.none) == ""
    assert R.as_wire_mode(None) == ""
    with pytest.raises(ValueError):
        R.as_wire_mode("int4")


def test_bf16_fp16_compressor_parity_retained():
    """The legacy host-side Compression path (torch/tf wrappers) must
    keep its semantics alongside the engine wire modes."""
    import jax.numpy as jnp
    from horovod_tpu.ops.compression import Compression
    x = jnp.asarray(np.linspace(-4, 4, 256, dtype=np.float32))
    for comp, wdt in ((Compression.fp16, jnp.bfloat16),
                      (Compression.fp16_ieee, jnp.float16)):
        wire, ctx = comp.compress(x)
        assert wire.dtype == wdt
        back = comp.decompress(wire, ctx)
        assert back.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                                   rtol=2 ** -7)
    # Quantized entries are engine-side: host compress is the identity.
    wire, ctx = Compression.int8.compress(x)
    assert wire is x and ctx is None


# ---------------------------------------------------------------------------
# precision resolution (the fall-back-to-fp32 gates)
# ---------------------------------------------------------------------------

def test_resolve_precision_gates():
    import jax.numpy as jnp
    cfg = hvd.global_state().config
    cfg.quant_min_bytes = 1024
    rp = R.resolve_precision
    f32, i32 = jnp.float32, jnp.int32
    assert rp("int8", hvd.Sum, f32, 1 << 20, cfg, 8) == "int8"
    assert rp("int8", hvd.Sum, f32, 512, cfg, 8) == "fp32"     # floor
    assert rp("int8", hvd.Sum, i32, 1 << 20, cfg, 8) == "fp32"  # int payload
    assert rp("int8", hvd.Min, f32, 1 << 20, cfg, 8) == "fp32"  # non-sum
    assert rp("int8", hvd.Sum, f32, 1 << 20, cfg, 1) == "fp32"  # no wire
    assert rp("int8", hvd.Sum, f32, 1 << 20, cfg, 512) == "fp32"  # overflow
    assert rp("bf16", hvd.Sum, jnp.bfloat16, 1 << 20, cfg, 8) == "fp32"
    assert rp("bf16", hvd.Average, f32, 64, cfg, 8) == "bf16"  # no floor
    cfg.wire_precision = "int8"   # engine default applies when unset
    assert rp("", hvd.Sum, f32, 1 << 20, cfg, 8) == "int8"
    with pytest.raises(ValueError):
        rp("int4", hvd.Sum, f32, 1 << 20, cfg, 8)


def test_adasum_never_quantizes():
    cfg = hvd.global_state().config
    import jax.numpy as jnp
    assert R.resolve_precision("int8", hvd.Adasum, jnp.float32,
                               1 << 20, cfg, 8) == "fp32"


# ---------------------------------------------------------------------------
# wire cost model — the acceptance anchor for effective bandwidth
# ---------------------------------------------------------------------------

def test_wire_cost_model_meets_bandwidth_target():
    """int8 wire must save >= 1.5x interconnect bytes vs the fp32 ring at
    >= 4 MB payloads (the EQuARX-style effective-bandwidth claim; the
    measured-wall-clock companion lives in collective_bench/BENCH_r06 —
    byte-width-insensitive CPU collectives cannot show it, a real
    interconnect does)."""
    for nbytes in (1 << 22, 1 << 24, 1 << 26):
        fp32 = R.ring_wire_bytes("fp32", nbytes, 8)
        for mode, floor in (("int8", 1.5), ("fp8", 1.5), ("bf16", 1.9)):
            saving = fp32 / R.ring_wire_bytes(mode, nbytes, 8)
            assert saving >= floor, (mode, nbytes, saving)
    # model sanity: one rank has no wire; scales shrink the saving at
    # small blocks but never below the 16-bit container's 2.66x ceiling.
    assert R.ring_wire_bytes("int8", 1 << 22, 1) == 0
    assert R.ring_wire_bytes("int8", 1 << 22, 8, block=64) > \
        R.ring_wire_bytes("int8", 1 << 22, 8, block=512)


def test_wire_saved_counter_accounts():
    from horovod_tpu.obs import REGISTRY
    before = _saved_total()
    rng = np.random.RandomState(5)
    parts = [rng.randn(70000).astype(np.float32) for _ in range(N)]
    hvd.to_numpy(C.allreduce(hvd.per_rank(parts), hvd.Sum,
                             precision="int8"))
    assert _saved_total() > before


def _saved_total() -> float:
    import horovod_tpu as hvd
    for fam in hvd.metrics():
        if fam["name"] == "hvd_wire_bytes_saved_total":
            return sum(s["value"] for s in fam["samples"])
    return 0.0


# ---------------------------------------------------------------------------
# adasum on the decomposed combine hook
# ---------------------------------------------------------------------------

def test_adasum_matches_dense_reference():
    """The shard-distributed tree (all_to_all + psum'd dots) must match
    the dense pairwise reference to fp32 tolerance."""
    rng = np.random.RandomState(11)
    vecs = [rng.randn(1003).astype(np.float32) for _ in range(N)]

    def pair(a, b):
        dot, na, nb = float(a @ b), float(a @ a), float(b @ b)
        ca = 1 - dot / (2 * na) if na > 0 else 1.0
        cb = 1 - dot / (2 * nb) if nb > 0 else 1.0
        return (ca * a + cb * b).astype(np.float32)

    ref = list(vecs)
    while len(ref) > 1:
        nxt = [pair(ref[i], ref[i + 1]) for i in range(0, len(ref) - 1, 2)]
        if len(ref) % 2:
            nxt.append(ref[-1])
        ref = nxt
    got = hvd.to_numpy(hvd.allreduce(hvd.per_rank(vecs), hvd.Adasum))
    np.testing.assert_allclose(got, ref[0], rtol=1e-4, atol=1e-5)


def test_in_context_quantized_allreduce():
    """optim/distributed's in-graph path: shared-scale quantize + narrow
    psum inside a mapped context."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.jaxcompat import shard_map
    state = hvd.global_state()
    mesh, axis = state.mesh, state.config.dp_axis_name

    def kern(v):
        return R.in_context_allreduce(v[0], axis, "int8", average=True)[None]

    fn = jax.jit(shard_map(kern, mesh=mesh, in_specs=P(axis),
                           out_specs=P(axis), check_vma=False))
    rng = np.random.RandomState(13)
    parts = np.stack([rng.randn(700).astype(np.float32) for _ in range(N)])
    out = np.asarray(fn(hvd.per_rank(list(parts))))
    exact = parts.mean(0)
    gmax = np.abs(parts).max()
    for row in out:
        np.testing.assert_allclose(row, exact,
                                   atol=1.5 * (N + 1) * gmax / 254.0)
