"""Bench regression sentinel (benchmarks/regress.py).

Pure-stdlib code under test — no jax, no rig.  Covers the gate's three
contractual behaviors (improvement passes, regression fails, device
kinds never cross-compare), the normalized-trajectory build from
synthetic BENCH/measured files, the allowlist, the live ``--extra``
ingestion, and the ``--inject`` self-test through the real CLI.
"""

import json

import pytest

from benchmarks import regress


def _rows(values, metric="tok_per_sec", kind="cpu", hib=True):
    return [{"round": f"r{i:02d}", "order": i * 1000, "metric": metric,
             "value": v, "unit": "", "device_kind": kind,
             "higher_is_better": hib, "source": "test"}
            for i, v in enumerate(values)]


def _by_status(results):
    out = {}
    for r in results:
        out.setdefault(r["status"], []).append(r)
    return out


# -- the gate ------------------------------------------------------------

def test_improvement_passes_and_is_reported():
    res = regress.check_series(_rows([1.0, 1.0, 1.0, 2.0]))
    assert [r["status"] for r in res] == ["improved"]


def test_regression_fails():
    [r] = regress.check_series(_rows([10.0, 10.0, 10.0, 5.0]))
    assert r["status"] == "regressed"
    assert r["delta_pct"] == pytest.approx(-50.0)
    assert r["baseline"] == pytest.approx(10.0)


def test_small_wobble_within_threshold_is_ok():
    [r] = regress.check_series(_rows([10.0, 10.1, 9.9, 9.0]))
    assert r["status"] == "ok"          # -10% < the 25% gate


def test_lower_is_better_flags_increases():
    [r] = regress.check_series(_rows([100.0, 100.0, 180.0],
                                     metric="p99_latency_ms", hib=False))
    assert r["status"] == "regressed" and r["delta_pct"] > 0


def test_mixed_device_kinds_never_cross_compare():
    # Same metric, TPU history then a CPU point 50x lower: two separate
    # series by construction, each too short to judge — NOT a regression.
    rows = (_rows([100.0, 101.0, 99.0], kind="TPU v5 lite")
            + _rows([2.0], kind="cpu"))
    res = regress.check_series(rows)
    by = {(r["metric"], r["device_kind"]): r["status"] for r in res}
    assert by[("tok_per_sec", "cpu")] == "single"
    assert by[("tok_per_sec", "TPU v5 lite")] == "ok"
    assert not _by_status(res).get("regressed")


def test_rolling_median_window_forgets_ancient_peaks():
    # A one-off spike 6 rounds ago must not poison today's baseline.
    vals = [50.0] + [10.0] * 6 + [9.0]
    [r] = regress.check_series(_rows(vals), window=5)
    assert r["status"] == "ok" and r["baseline"] == pytest.approx(10.0)


def test_allowlist_downgrades_to_allowed():
    allow = [{"metric": "tok_per_sec", "device_kind": "cpu",
              "reason": "container changed"}]
    [r] = regress.check_series(_rows([10.0, 10.0, 3.0]), allowlist=allow)
    assert r["status"] == "allowed" and r["reason"] == "container changed"
    # Wildcard device kind matches too; a different metric does not.
    [r2] = regress.check_series(
        _rows([10.0, 10.0, 3.0]),
        allowlist=[{"metric": "tok_per_sec", "device_kind": "*",
                    "reason": "any kind"}])
    assert r2["status"] == "allowed"
    [r3] = regress.check_series(
        _rows([10.0, 10.0, 3.0]),
        allowlist=[{"metric": "other", "reason": "no"}])
    assert r3["status"] == "regressed"


def test_only_rounds_restricts_judgement_to_live_series():
    hist = _rows([10.0, 10.0, 10.0])
    live = [{"round": "live", "order": 10 ** 9, "metric": "tok_per_sec",
             "value": 2.0, "unit": "", "device_kind": "cpu",
             "higher_is_better": True, "source": "sweep"}]
    res = regress.check_series(hist + live, only_rounds={"live"})
    assert len(res) == 1 and res[0]["status"] == "regressed"
    # Without live rows nothing is judged at all.
    assert regress.check_series(hist, only_rounds={"live"}) == []


# -- normalization -------------------------------------------------------

def test_build_trajectory_from_synthetic_history(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "parsed": {"metric": "train_tok", "value": 100.0, "unit": "tok/s",
                   "device_kind": "cpu", "mfu": 0.5}}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "parsed": {"metric": "train_tok", "value": 120.0, "unit": "tok/s",
                   "device_kind": "cpu"},
        "rows": [{"op": "allreduce", "bytes": 4096, "ranks": 8,
                  "busbw_GBs": 0.5}]}))
    measured = tmp_path / "measured.jsonl"
    measured.write_text(json.dumps(
        {"metric": "train_tok", "value": 130.0, "unit": "tok/s",
         "device_kind": "cpu"}) + "\n" + "not json\n")
    traj = regress.build_trajectory(repo=str(tmp_path),
                                    measured=str(measured))
    series = {(r["metric"], r["device_kind"]) for r in traj["rows"]}
    assert ("train_tok", "cpu") in series
    assert ("train_tok_mfu", "cpu") in series
    assert ("allreduce_fp32_monolithic_busbw_GBs@4KB",
            "cpu-rig-np8") in series
    tt = [r for r in traj["rows"]
          if r["metric"] == "train_tok" and r["device_kind"] == "cpu"]
    assert [r["value"] for r in sorted(tt, key=lambda r: r["order"])] \
        == [100.0, 120.0, 130.0]   # rounds first, measured after
    assert traj["rounds"] == ["measured", "r01", "r02"]


def test_ingest_extra_parses_sweep_rows_only(tmp_path):
    sweep = tmp_path / "sweep.jsonl"
    sweep.write_text("\n".join([
        json.dumps({"op": "allreduce", "bytes": 1 << 20, "ranks": 8,
                    "wire_precision": "fp32", "busbw_GBs": 0.4,
                    "model_efficiency": 1.0}),
        json.dumps({"metric": "allreduce_busbw_peak", "value": 0.4}),
        "garbage",
    ]))
    rows = regress.ingest_extra(str(sweep))
    assert len(rows) == 1
    assert rows[0]["metric"] == "allreduce_fp32_monolithic_busbw_GBs@1MB"
    assert rows[0]["device_kind"] == "cpu-rig-np8"
    assert rows[0]["round"] == "live"


# -- the CLI, end to end -------------------------------------------------

def _write_traj(tmp_path, values):
    path = tmp_path / "traj.json"
    path.write_text(json.dumps({"rows": _rows(values)}))
    return str(path)


def test_cli_check_passes_then_inject_fails(tmp_path, capsys):
    path = _write_traj(tmp_path, [10.0, 10.0, 10.5])
    assert regress.main(["--check", "--trajectory", path]) == 0
    assert regress.main(["--check", "--trajectory", path,
                         "--inject", "tok_per_sec"]) == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out and "FAIL" in out


def test_cli_inject_explicit_value_and_kind(tmp_path):
    path = _write_traj(tmp_path, [10.0, 10.0, 10.0])
    assert regress.main(["--check", "--trajectory", path,
                         "--inject", "tok_per_sec@cpu=1.0"]) == 1
    with pytest.raises(SystemExit):
        regress.main(["--check", "--trajectory", path,
                      "--inject", "no_such_metric"])


def test_cli_inject_handles_at_sign_in_metric_names(tmp_path):
    # Per-size sweep series contain '@' in the metric itself: an exact
    # name match wins, and only a trailing '@kind' splits off.
    rows = _rows([0.4, 0.4, 0.4],
                 metric="allreduce_fp32_monolithic_busbw_GBs@1MB",
                 kind="cpu-rig-np8")
    path = tmp_path / "traj.json"
    path.write_text(json.dumps({"rows": rows}))
    assert regress.main([
        "--check", "--trajectory", str(path),
        "--inject", "allreduce_fp32_monolithic_busbw_GBs@1MB"]) == 1
    assert regress.main([
        "--check", "--trajectory", str(path),
        "--inject",
        "allreduce_fp32_monolithic_busbw_GBs@1MB@cpu-rig-np8"]) == 1


def test_cli_extra_gates_live_rows(tmp_path):
    # History for the 1MB np8 series, then a live sweep 10x slower:
    # fails even at the loose live threshold.
    hist = _rows([0.40, 0.41, 0.39],
                 metric="allreduce_fp32_monolithic_busbw_GBs@1MB",
                 kind="cpu-rig-np8")
    path = tmp_path / "traj.json"
    path.write_text(json.dumps({"rows": hist}))
    sweep = tmp_path / "sweep.jsonl"
    sweep.write_text(json.dumps(
        {"op": "allreduce", "bytes": 1 << 20, "ranks": 8,
         "busbw_GBs": 0.04}) + "\n")
    assert regress.main(["--check", "--trajectory", str(path),
                         "--extra", str(sweep)]) == 1
    # The same live value within the threshold passes.
    sweep.write_text(json.dumps(
        {"op": "allreduce", "bytes": 1 << 20, "ranks": 8,
         "busbw_GBs": 0.35}) + "\n")
    assert regress.main(["--check", "--trajectory", str(path),
                         "--extra", str(sweep)]) == 0


def test_committed_trajectory_is_fresh_and_passes():
    """The acceptance gate itself: the committed BENCH_trajectory.json
    must rebuild identically from BENCH_r*.json + measured.jsonl and
    clear the regression check (historical drops are allowlisted with
    reasons in benchmarks/regress_allow.json)."""
    assert regress.main(["--check"]) == 0
