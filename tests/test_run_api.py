"""Programmatic function launcher — † ``horovod.run`` parity
(``horovod/runner/__init__.py``; upstream tests: ``test/integration/
test_interactiverun.py``).

`run_func` ships a cloudpickled function over the job KV store, executes it
on every rank as a real ``launch_workers`` job, and returns the rank-ordered
results — these tests drive that full circle with live subprocesses.
"""

import os

import pytest

from horovod_tpu.runner.api import kv_get_blob, kv_put_blob, run_func

pytestmark = pytest.mark.integration


def _rank_info(mult):
    return {
        "rank": int(os.environ["HVDTPU_CROSS_RANK"]),
        "size": int(os.environ["HVDTPU_CROSS_SIZE"]),
        "x": int(os.environ["HVDTPU_CROSS_RANK"]) * mult,
    }


def test_run_func_rank_ordered_results():
    out = run_func(_rank_info, args=(10,), np=2)
    assert [o["rank"] for o in out] == [0, 1]
    assert all(o["size"] == 2 for o in out)
    assert [o["x"] for o in out] == [0, 10]


def test_run_func_pickles_closures_by_value():
    base = 5  # captured — only cloudpickle-by-value can ship this lambda
    out = run_func(
        lambda: base + int(os.environ["HVDTPU_CROSS_RANK"]), np=2)
    assert out == [5, 6]


def test_run_func_worker_exception_propagates():
    def boom():
        if os.environ["HVDTPU_CROSS_RANK"] == "1":
            raise ValueError("rank1 exploded")
        return "ok"

    with pytest.raises(RuntimeError, match="rank1 exploded"):
        run_func(boom, np=2)


def test_run_func_failure_surfaces_past_hung_peer():
    """A rank blocked forever must not hide another rank's traceback:
    the collector sweeps all ranks, so the fast failure is collected and
    attached even though rank 0 never reports."""
    def hang_or_boom():
        if os.environ["HVDTPU_CROSS_RANK"] == "1":
            raise ValueError("fast failure")
        import time
        time.sleep(300)  # killed by the monitor once rank 1 exits

    with pytest.raises(RuntimeError, match="fast failure"):
        run_func(hang_or_boom, np=2)


def test_worker_module_does_not_shadow_function():
    import horovod_tpu.runner as R
    import horovod_tpu.runner._run_func_worker  # noqa: F401
    assert callable(R.run_func)


def _allreduce_job(scale):
    """A real hvd job: init from the injected env and allreduce."""
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    hvd.init()
    out = hvd.to_numpy(hvd.allreduce(
        hvd.from_local(np.full((1, 4), float(hvd.rank()) * scale,
                               np.float32)),
        hvd.Sum))
    hvd.shutdown()
    # sum over ranks 0..n-1 of r*scale
    n = int(os.environ["HVDTPU_CROSS_SIZE"])
    expect = scale * n * (n - 1) / 2
    assert float(out[0]) == expect, (float(out[0]), expect)
    return float(out[0])


def test_run_func_full_collective_job():
    env = {"PALLAS_AXON_POOL_IPS": ""}
    out = run_func(_allreduce_job, args=(2.0,), np=2, extra_env=env)
    assert out == [2.0, 2.0]


def test_kv_blob_chunking_roundtrip():
    from horovod_tpu._native import KvClient, KvServer
    srv = KvServer(secret="s")
    try:
        kv = KvClient("127.0.0.1", srv.port, secret="s")
        blob = os.urandom((4 << 20) + 12345)  # forces 2 chunks
        kv_put_blob(kv, "t/blob", blob)
        assert kv_get_blob(kv, "t/blob", timeout_ms=2000) == blob
        kv.close()
    finally:
        srv.stop()
