"""Programmatic function launcher — † ``horovod.run`` parity
(``horovod/runner/__init__.py``; upstream tests: ``test/integration/
test_interactiverun.py``).

`run_func` ships a cloudpickled function over the job KV store, executes it
on every rank as a real ``launch_workers`` job, and returns the rank-ordered
results — these tests drive that full circle with live subprocesses.
"""

import os

import pytest

from horovod_tpu.runner.api import kv_get_blob, kv_put_blob, run_func

pytestmark = pytest.mark.integration


def _rank_info(mult):
    return {
        "rank": int(os.environ["HVDTPU_CROSS_RANK"]),
        "size": int(os.environ["HVDTPU_CROSS_SIZE"]),
        "x": int(os.environ["HVDTPU_CROSS_RANK"]) * mult,
    }


def test_run_func_rank_ordered_results():
    out = run_func(_rank_info, args=(10,), np=2)
    assert [o["rank"] for o in out] == [0, 1]
    assert all(o["size"] == 2 for o in out)
    assert [o["x"] for o in out] == [0, 10]


def test_run_func_pickles_closures_by_value():
    base = 5  # captured — only cloudpickle-by-value can ship this lambda
    out = run_func(
        lambda: base + int(os.environ["HVDTPU_CROSS_RANK"]), np=2)
    assert out == [5, 6]


def test_run_func_worker_exception_propagates():
    def boom():
        if os.environ["HVDTPU_CROSS_RANK"] == "1":
            raise ValueError("rank1 exploded")
        return "ok"

    with pytest.raises(RuntimeError, match="rank1 exploded"):
        run_func(boom, np=2)


def test_run_func_failure_surfaces_past_hung_peer():
    """A rank blocked forever must not hide another rank's traceback:
    the collector sweeps all ranks, so the fast failure is collected and
    attached even though rank 0 never reports."""
    def hang_or_boom():
        if os.environ["HVDTPU_CROSS_RANK"] == "1":
            raise ValueError("fast failure")
        import time
        time.sleep(300)  # killed by the monitor once rank 1 exits

    with pytest.raises(RuntimeError, match="fast failure"):
        run_func(hang_or_boom, np=2)


def test_worker_module_does_not_shadow_function():
    import horovod_tpu.runner as R
    import horovod_tpu.runner._run_func_worker  # noqa: F401
    assert callable(R.run_func)


def _allreduce_job(scale):
    """A real hvd job: init from the injected env and allreduce."""
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    hvd.init()
    out = hvd.to_numpy(hvd.allreduce(
        hvd.from_local(np.full((1, 4), float(hvd.rank()) * scale,
                               np.float32)),
        hvd.Sum))
    hvd.shutdown()
    # sum over ranks 0..n-1 of r*scale
    n = int(os.environ["HVDTPU_CROSS_SIZE"])
    expect = scale * n * (n - 1) / 2
    assert float(out[0]) == expect, (float(out[0]), expect)
    return float(out[0])


def test_run_func_full_collective_job():
    env = {"PALLAS_AXON_POOL_IPS": ""}
    out = run_func(_allreduce_job, args=(2.0,), np=2, extra_env=env)
    assert out == [2.0, 2.0]


def test_kv_blob_chunking_roundtrip():
    from horovod_tpu._native import KvClient, KvServer
    srv = KvServer(secret="s")
    try:
        kv = KvClient("127.0.0.1", srv.port, secret="s")
        blob = os.urandom((4 << 20) + 12345)  # forces 2 chunks
        kv_put_blob(kv, "t/blob", blob)
        assert kv_get_blob(kv, "t/blob", timeout_ms=2000) == blob
        kv.close()
    finally:
        srv.stop()


def _flagship_losses_on(mesh, batch, n_steps=4):
    """Shared 4-step flagship train loop: one definition serves both the
    multi-process worker (shipped by value) and the in-process oracle."""
    import jax
    import optax
    from horovod_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0), mesh)
    tx = optax.adam(1e-2)
    opt = jax.jit(tx.init)(params)
    step = llama.make_train_step(cfg, mesh, tx)
    losses = []
    for _ in range(n_steps):
        params, opt, loss = step(params, opt, batch)
        losses.append(float(loss))
    return losses


def _flagship_tokens():
    import numpy as np
    from horovod_tpu.models import llama
    return np.random.RandomState(0).randint(
        0, llama.LlamaConfig.tiny().vocab_size, (8, 33))


@pytest.mark.parametrize("axis", ["dp", "tp", "pp"])
def test_run_func_flagship_on_multiprocess_global_mesh(axis):
    """The real multi-HOST path: two PROCESSES (one device each) form a
    jax.distributed global mesh and run the flagship's actual train step
    over it — per axis, the collectives that cross the process boundary:
    dp = GSPMD gradient psums, tp = per-layer Megatron all-gathers/psums,
    pp = the pipeline's ppermute handoffs + the 1F1B cotangent returns
    (the 'pp tolerates DCN' design claim, exercised for real).  The
    4-step loss trajectory must be bitwise-identical on both ranks AND
    match the single-process oracle on the same mesh shape."""

    def work(axis):
        from horovod_tpu.utils.cpurig import force_cpu_platform
        force_cpu_platform(1)
        import jax
        import jax.numpy as jnp
        import horovod_tpu as hvd
        hvd.init()
        from jax.sharding import NamedSharding, PartitionSpec as P
        from horovod_tpu.parallel import MeshConfig, build_mesh

        assert jax.device_count() == 2 and jax.process_count() == 2
        mesh = build_mesh(MeshConfig(**{axis: 2}))
        tokens = _flagship_tokens()
        sharding = NamedSharding(mesh, P(("dp", "fsdp")))
        me = hvd.rank()
        local = tokens[4 * me:4 * (me + 1)] if axis == "dp" else tokens
        batch = {"tokens": jax.make_array_from_process_local_data(
            sharding, jnp.asarray(local, jnp.int32), (8, 33))}
        return _flagship_losses_on(mesh, batch)

    res = run_func(work, args=(axis,), np=2)
    assert res[0] == res[1], (res[0], res[1])
    assert res[0][-1] < res[0][0], res[0]

    # Single-process oracle on the same mesh shape and data.
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from horovod_tpu.parallel import MeshConfig, build_mesh
    mesh = build_mesh(MeshConfig(**{axis: 2}), devices=jax.devices()[:2])
    batch = {"tokens": jax.device_put(
        jnp.asarray(_flagship_tokens(), jnp.int32),
        NamedSharding(mesh, P(("dp", "fsdp"))))}
    oracle = _flagship_losses_on(mesh, batch)
    np.testing.assert_allclose(res[0], oracle, rtol=1e-5)


def test_run_func_two_devices_per_process():
    """np=2 x 2 devices per process (round-4 verdict ask #2: local_size>1
    exercised CROSS-process): ``from_local``/``replicate_local``/
    ``to_local`` assemble global arrays via
    ``make_array_from_single_device_arrays`` from multi-row process-local
    data, and the flagship step runs on the 4-device global mesh."""

    def work():
        from horovod_tpu.utils.cpurig import force_cpu_platform
        force_cpu_platform(2)                 # 2 local devices
        import jax
        import jax.numpy as jnp
        import numpy as np
        import horovod_tpu as hvd
        hvd.init()
        assert jax.process_count() == 2 and jax.device_count() == 4
        assert hvd.local_size() == 2 and hvd.size() == 4

        # from_local at local_size=2: this process contributes TWO rows.
        me = jax.process_index()
        rows = np.stack([np.full((3,), float(2 * me + i), np.float32)
                         for i in range(2)])
        g = hvd.from_local(rows)
        s = hvd.to_numpy(hvd.allreduce(g, hvd.Sum))
        np.testing.assert_allclose(s[0], [6.0, 6.0, 6.0])  # 0+1+2+3

        # replicate_local at local_size=2: one payload, both local rows.
        r = hvd.replicate_local(np.full((2,), 7.0 + me, np.float32))
        loc = hvd.to_local(hvd.allreduce(r, hvd.Average))
        np.testing.assert_allclose(loc, 7.5)  # mean(7, 7, 8, 8)

        # Flagship step over the 4-device global dp mesh, data fed via
        # make_array_from_process_local_data with 2-device local shards.
        from jax.sharding import NamedSharding, PartitionSpec as P
        from horovod_tpu.parallel import MeshConfig, build_mesh
        mesh = build_mesh(MeshConfig(dp=4))
        tokens = _flagship_tokens()
        sharding = NamedSharding(mesh, P(("dp", "fsdp")))
        local = tokens[4 * me:4 * (me + 1)]
        batch = {"tokens": jax.make_array_from_process_local_data(
            sharding, jnp.asarray(local, jnp.int32), (8, 33))}
        losses = _flagship_losses_on(mesh, batch)
        hvd.shutdown()
        return losses

    res = run_func(work, np=2)
    assert res[0] == res[1], (res[0], res[1])
    assert res[0][-1] < res[0][0], res[0]
