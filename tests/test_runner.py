"""Launcher: host parsing, rank assignment, end-to-end multi-process jobs.

Mirrors † ``test/single/test_run.py`` (arg/host parsing, command
construction) and † ``test/integration/test_static_run.py`` (really exec the
launcher end-to-end on localhost).
"""

import os
import subprocess
import sys
import time

import pytest

from horovod_tpu.runner import parse_hosts
from horovod_tpu.runner.hosts import assign_ranks
from horovod_tpu.runner.launch import build_parser, _knob_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# parsing
# ---------------------------------------------------------------------------

def test_parse_hosts():
    hs = parse_hosts("a:2,b:4")
    assert [(h.hostname, h.slots) for h in hs] == [("a", 2), ("b", 4)]
    assert parse_hosts("solo")[0].slots == 1


@pytest.mark.parametrize("bad", ["", ":3", "h:x", "h:0"])
def test_parse_hosts_bad(bad):
    with pytest.raises(ValueError):
        parse_hosts(bad)


def test_assign_ranks():
    hs = parse_hosts("a:2,b:2")
    assert assign_ranks(hs, 3) == [(0, "a", 0), (1, "a", 1), (2, "b", 0)]
    with pytest.raises(ValueError):
        assign_ranks(hs, 5)


def test_cli_knob_env():
    args = build_parser().parse_args(
        ["-np", "2", "--fusion-threshold-mb", "8", "--cycle-time-ms", "2.5",
         "--autotune", "--log-level", "debug", "--", "python", "x.py"])
    env = _knob_env(args)
    assert env["HVDTPU_FUSION_THRESHOLD"] == str(8 * 1024 * 1024)
    assert env["HVDTPU_CYCLE_TIME"] == "2.5"
    assert env["HVDTPU_AUTOTUNE"] == "1"
    assert env["HVDTPU_LOG_LEVEL"] == "debug"


def test_cli_platform_knob(monkeypatch, tmp_path):
    args = build_parser().parse_args(
        ["-np", "2", "--platform", "cpu", "--", "python", "x.py"])
    assert _knob_env(args)["HVDTPU_PLATFORM"] == "cpu"
    import horovod_tpu.config as config_mod
    monkeypatch.setenv("HVDTPU_PLATFORM", "CPU")  # normalized, not passed raw
    assert config_mod.from_env().platform == "cpu"
    monkeypatch.setenv("HVDTPU_PLATFORM", "gpu")  # fails at the knob, not jax
    with pytest.raises(ValueError):
        config_mod.from_env()
    monkeypatch.delenv("HVDTPU_PLATFORM")
    cfgf = tmp_path / "c.yaml"
    cfgf.write_text("platform: banana\n")
    with pytest.raises(ValueError):
        config_mod.from_yaml(str(cfgf))
    cfgf.write_text("platform: TPU\n")
    assert config_mod.from_yaml(str(cfgf)).platform == "tpu"


def test_cli_config_file(tmp_path):
    cfg = tmp_path / "c.yaml"
    cfg.write_text("cycle_time_ms: 7.5\nautotune: true\n")
    args = build_parser().parse_args(
        ["-np", "1", "--config-file", str(cfg), "--", "true"])
    env = _knob_env(args)
    assert env["HVDTPU_CYCLE_TIME"] == "7.5"
    assert env["HVDTPU_AUTOTUNE"] == "1"


# ---------------------------------------------------------------------------
# end-to-end († test_static_run)
# ---------------------------------------------------------------------------

def _hvdrun(np_, script_args, timeout=240, extra_env=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)  # workers force CPU
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", str(np_), "--",
         sys.executable] + script_args,
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


@pytest.mark.integration
@pytest.mark.parametrize("np_", [2, 8])
def test_hvdrun_collective_battery(np_):
    """The full verb battery over real negotiated transport — at the
    historical 2-process rig and at np=8 (controller round-barrier,
    fused grouped dispatch, ragged allgatherv, non-uniform alltoallv)."""
    res = _hvdrun(np_, [os.path.join(REPO, "tests", "mp_train_worker.py")],
                  timeout=120 + 30 * np_)
    assert res.returncode == 0, res.stdout + res.stderr
    for r in range(np_):
        assert f"rank {r}: OK" in res.stdout, res.stdout


@pytest.mark.integration
def test_hvdrun_worker_failure_kills_job():
    code = ("import sys, os; "
            "sys.exit(3 if os.environ['HVDTPU_CROSS_RANK'] == '1' else 0)")
    res = _hvdrun(2, ["-c", code])
    assert res.returncode == 3


@pytest.mark.integration
def test_hvdrun_no_command():
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "1"],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert res.returncode == 2
    assert "no command" in res.stderr


@pytest.mark.integration
@pytest.mark.parametrize("np_", [2, 4])
def test_hvdrun_quantized_allreduce_parity(np_):
    """Block-scaled int8/fp8/bf16 wire modes over real negotiated
    transport: parity within the documented tolerance at np=2 (the
    ci.yaml quantized-parity job) and np=4, plus mixed-mode fusion-group
    consistency across processes (divergent groups would hang, so
    completion is the assertion)."""
    res = _hvdrun(np_, [os.path.join(REPO, "tests", "mp_quant_worker.py")],
                  timeout=120 + 30 * np_)
    assert res.returncode == 0, res.stdout + res.stderr
    for r in range(np_):
        assert f"rank {r}: QUANT-OK" in res.stdout, res.stdout


@pytest.mark.integration
@pytest.mark.parametrize("np_", [2, 4])
def test_hvdrun_decomposed_allreduce_parity(np_):
    """Decomposed (ops/sched) vs monolithic allreduce over real
    negotiated transport: BIT-exact for int8/fp8 at both np=2 (the
    ci.yaml decomposed-parity job) and np=4, BIT-exact for fp32 at np=2
    and <=2-ulp at np=4 (ring association order — see the worker
    docstring), plus mixed-schedule fusion-group consistency and the
    join/rebuild path (a joined rank reconstructs the chunked program
    from the meta's ``sc`` field; divergence hangs, so completion is
    part of the assertion)."""
    res = _hvdrun(np_, [os.path.join(REPO, "tests", "mp_sched_worker.py")],
                  timeout=120 + 30 * np_)
    assert res.returncode == 0, res.stdout + res.stderr
    for r in range(np_):
        assert f"rank {r}: SCHED-OK" in res.stdout, res.stdout


@pytest.mark.integration
@pytest.mark.parametrize("np_", [2, 4])
def test_hvdrun_compiled_allreduce_parity(np_):
    """Compiled single-program (ops/sched/compiled) vs monolithic
    allreduce over real negotiated transport (the ci.yaml
    compiled-parity job): BIT-exact for int8/fp8 at both sizes,
    BIT-exact for fp32 at np=2 and <=2-ulp at np=4, with the engine's
    per-chunk dispatch counter pinned at ZERO for the whole battery
    (one cached jitted program per fused group), a mixed-mode phase
    where a decomposed-pinned rank adopts the coordinator's echoed
    compiled descriptor before fusion (divergent backends deadlock on
    per-executable channel IDs, so completion is part of the
    assertion), and the join/rebuild path with a compiled ``sc``
    descriptor."""
    res = _hvdrun(np_, [os.path.join(REPO, "tests", "mp_sched_worker.py")],
                  timeout=120 + 30 * np_,
                  extra_env={"HVDTPU_TEST_MODE": "compiled"})
    assert res.returncode == 0, res.stdout + res.stderr
    for r in range(np_):
        assert f"rank {r}: COMPILED-OK" in res.stdout, res.stdout


@pytest.mark.integration
@pytest.mark.parametrize("np_", [2, 4])
def test_hvdrun_zero1_parity(np_):
    """ZeRO-1 sharded-optimizer wire pattern and the bucketed backward
    path over real negotiated transport (the ci.yaml zero1-parity job):
    reduce-scatter -> 1/n local update -> parameter allgather matches
    the dense allreduce step BIT-exact at np=2 / <=2-ulp at np=4;
    bucketed vs unbucketed eager reduction bit-exact for fp32 AND int8
    (block-aligned entries keep quant scales identical under
    regrouping); the compiled bucketed pass rides the single-program
    backend with zero new per-chunk dispatches; and the join/rebuild
    path runs through the bucketed enqueue+nudge loop."""
    res = _hvdrun(np_, [os.path.join(REPO, "tests", "mp_sched_worker.py")],
                  timeout=120 + 30 * np_,
                  extra_env={"HVDTPU_TEST_MODE": "zero"})
    assert res.returncode == 0, res.stdout + res.stderr
    for r in range(np_):
        assert f"rank {r}: ZERO-OK" in res.stdout, res.stdout


@pytest.mark.integration
def test_hvdrun_hierarchical_parity():
    """Chunked+tiered (``hier:2:2``) vs flat allreduce over real
    negotiated transport at np=4 as a 2x2 tier mesh (the ci.yaml
    hierarchical-parity job): int8 BIT-exact, fp8 bounded (fp16
    accumulator — see the worker docstring), fp32 <=2-ulp, a quantized
    cross-tier hop under an fp32 fast tier, mixed flat+tiered fusion
    groups, the join/rebuild path with a tiered ``sc`` descriptor, and
    rank-labeled ``hvd_perf_tier_*`` gauges on ``/cluster``.  A
    dispatch-counter guard inside the worker proves the tiered executor
    ran (a silent flat fallback would make parity vacuous)."""
    res = _hvdrun(4, [os.path.join(REPO, "tests", "mp_sched_worker.py")],
                  timeout=360,
                  extra_env={"HVDTPU_TEST_MODE": "hier",
                             "HVDTPU_HIERARCHICAL_LOCAL_SIZE": "2"})
    assert res.returncode == 0, res.stdout + res.stderr
    for r in range(4):
        assert f"rank {r}: HIER-OK" in res.stdout, res.stdout


@pytest.mark.integration
def test_hvdrun_join_uneven_inputs():
    """† test_horovod_join: rank 0 runs 3 steps, rank 1 runs 5; the job
    completes (no deadlock) and surviving-step allreduces are correct."""
    res = _hvdrun(2, [os.path.join(REPO, "tests", "mp_join_worker.py")])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "rank 0: JOIN-OK last=1" in res.stdout
    assert "rank 1: JOIN-OK last=1" in res.stdout


@pytest.mark.integration
def test_hvdrun_np4_grouped_and_process_set():
    """Round-2 verdict #5: the fused grouped path and a process-set
    collective over real negotiated transport at np=4 (the controller's
    round-barrier beyond the 2-rank world)."""
    res = _hvdrun(4, [os.path.join(REPO, "tests", "mp_np4_worker.py")])
    assert res.returncode == 0, res.stdout + res.stderr
    for r in range(4):
        assert f"rank {r}: NP4-OK" in res.stdout, res.stdout


@pytest.mark.integration
def test_hvdrun_np4_stall_detection():
    """One rank diverges (never submits); every submitting rank must get
    the stall warning + HorovodInternalError shutdown while the diverged
    rank exits cleanly († stall_inspector.cc semantics at np=4)."""
    res = _hvdrun(4, [os.path.join(REPO, "tests", "mp_np4_worker.py")],
                  extra_env={
                      "HVDTPU_TEST_MODE": "stall",
                      "HVDTPU_STALL_CHECK_TIME_SECONDS": "2",
                      "HVDTPU_STALL_SHUTDOWN_TIME_SECONDS": "4",
                  })
    assert res.returncode == 0, res.stdout + res.stderr
    for r in range(3):
        assert f"rank {r}: STALL-ERR-OK" in res.stdout, res.stdout
    assert "rank 3: STALL-BYSTANDER-OK" in res.stdout, res.stdout


@pytest.mark.integration
@pytest.mark.slow  # tier-1 budget: covered by CI multiprocess-e2e
def test_hvdrun_sync_batch_norm():
    """† sync_batch_norm semantics over 2 real processes with different
    shards, against a concatenated-batch BatchNorm oracle."""
    res = _hvdrun(2, [os.path.join(REPO, "tests", "mp_sync_bn_worker.py")])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "rank 0: SYNC-BN-OK" in res.stdout
    assert "rank 1: SYNC-BN-OK" in res.stdout


@pytest.mark.integration
@pytest.mark.slow  # tier-1 budget: covered by CI multiprocess-e2e
def test_hvdrun_torch_distributed_optimizer():
    """†3.2: the torch hot path over 2 real processes with different data."""
    res = _hvdrun(2, [os.path.join(REPO, "tests", "mp_torch_worker.py")])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "rank 0: TORCH-OK" in res.stdout
    assert "rank 1: TORCH-OK" in res.stdout


@pytest.mark.integration
def test_hvdrun_elastic_kill_blacklist_relaunch(tmp_path):
    """† test/integration/elastic: full elastic circle through the CLI.

    np=2 via a discovery script naming two 'hosts' (localhost and
    127.0.0.1 — distinct for blacklisting, both exec'd locally); rank 1
    hard-crashes at step 3; the ElasticDriver must blacklist its host,
    relaunch at np=1, and the survivor must resume from the last
    state.commit() with exact value continuity (w follows
    ``w <- size*(w+1)``: 2,6,14 at np=2, then 15,16,17 at np=1)."""
    discover = tmp_path / "discover.sh"
    discover.write_text("#!/bin/sh\necho localhost:1\necho 127.0.0.1:1\n")
    discover.chmod(0o755)
    state = tmp_path / "state.json"
    log = tmp_path / "train.log"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["HVDTPU_TEST_STATE"] = str(state)
    env["HVDTPU_TEST_LOG"] = str(log)
    env["HVDTPU_TEST_KILL"] = "1"
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         "--min-np", "1", "--max-np", "2",
         "--host-discovery-script", str(discover), "--",
         sys.executable, os.path.join(REPO, "tests", "mp_elastic_worker.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert res.returncode == 0, res.stdout + res.stderr
    lines = log.read_text().splitlines()
    assert "START rank=0 size=2 resume_step=0 w=0.0" in lines
    assert "CRASH rank=1 step=3" in lines
    # Relaunched at np=1 from the last commit (step 3, w=14), not from 0.
    assert "START rank=0 size=1 resume_step=3 w=14.0" in lines
    assert "DONE rank=0 size=1 step=6 w=17.0" in lines
    import json as _json
    final = _json.loads(state.read_text())
    assert final == {"step": 6, "w": 17.0}


@pytest.mark.integration
@pytest.mark.slow  # tier-1 budget (~21s grow circle): CI multiprocess-e2e runs it
def test_hvdrun_elastic_grow_uses_new_host(tmp_path):
    """Scale-UP circle: the job starts at np=1; mid-run the discovery
    file gains a second host; the driver's growth watcher bumps the
    membership epoch, the worker exits with the restart code at its next
    commit, and the driver relaunches at np=2 — resuming from the last
    commit (at size 1, w == step exactly) with both ranks training."""
    hostsfile = tmp_path / "hosts.txt"
    hostsfile.write_text("localhost:1\n")
    discover = tmp_path / "discover.sh"
    discover.write_text(f"#!/bin/sh\ncat {hostsfile}\n")
    discover.chmod(0o755)
    state = tmp_path / "state.json"
    log = tmp_path / "train.log"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["HVDTPU_TEST_STATE"] = str(state)
    env["HVDTPU_TEST_LOG"] = str(log)
    env["HVDTPU_TEST_TOTAL"] = "40"
    env["HVDTPU_TEST_STEP_DELAY"] = "0.4"
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "1",
         "--min-np", "1", "--max-np", "2",
         "--host-discovery-script", str(discover), "--",
         sys.executable, os.path.join(REPO, "tests", "mp_elastic_worker.py")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)
    try:
        # Let the np=1 incarnation commit a few steps, then add capacity.
        deadline = time.time() + 120
        while time.time() < deadline:
            if log.exists() and sum(
                    1 for ln in log.read_text().splitlines()
                    if ln.startswith("STEP")) >= 3:
                break
            time.sleep(0.5)
        hostsfile.write_text("localhost:1\n127.0.0.1:1\n")
        out, _ = proc.communicate(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out
    lines = log.read_text().splitlines()
    assert "START rank=0 size=1 resume_step=0 w=0.0" in lines
    # The relaunch runs at size 2 and resumed from the exact commit
    # (w == step at size 1).
    restart = [ln for ln in lines
               if ln.startswith("START rank=0 size=2 resume_step=")]
    assert restart, lines
    resumed = restart[0].split("resume_step=")[1].split()
    assert float(resumed[1].split("=")[1]) == float(resumed[0]) > 0
    assert any(ln.startswith("STEP rank=1 size=2") for ln in lines), lines
    assert any(ln.startswith("DONE rank=0 size=2 step=40") for ln in lines)
    import json as _json
    assert _json.loads(state.read_text())["step"] == 40


@pytest.mark.integration
def test_hvdrun_elastic_flags_require_discovery():
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         "--min-np", "1", "--", "python", "x.py"],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert res.returncode == 2
    assert "host-discovery-script" in res.stderr


@pytest.mark.integration
def test_hvdrun_check_build():
    """† horovodrun --check-build prints capabilities without launching."""
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "--check-build"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "Available Frameworks" in res.stdout
    assert "[X] JAX / Flax" in res.stdout
    assert "Available Tensor Operations" in res.stdout


@pytest.mark.integration
def test_hvdrun_missing_np():
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "--", "python", "x.py"],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert res.returncode == 2
    assert "num-proc" in res.stderr


@pytest.mark.integration
@pytest.mark.slow  # tier-1 budget (~75s, heaviest e2e): CI multiprocess-e2e runs it
def test_hvdrun_elastic_checkpoint_world_size_circle(tmp_path):
    """Elastic x orbax checkpoint across WORLD SIZES (VERDICT r3 #5): train
    at np=4, rank 2 crashes (its 2-slot host is blacklisted -> np=2), the
    relaunch restores params+adam moments+step from orbax; mid-run the
    discovery file gains a third host -> grow circle back to np=4 with
    another restore.  The worker trains full-batch (gradient averaging is
    world-size-invariant), so EVERY logged loss must match the
    uninterrupted single-process oracle — which only holds if the model
    and optimizer state round-trip exactly through every restart."""
    from horovod_tpu.runner.cluster import local_ip
    my_ip = local_ip()  # the launcher's own notion of "this machine"
    assert my_ip not in ("localhost", "127.0.0.1"), my_ip
    hostsfile = tmp_path / "hosts.txt"
    hostsfile.write_text("localhost:2\n127.0.0.1:2\n")
    discover = tmp_path / "discover.sh"
    discover.write_text(f"#!/bin/sh\ncat {hostsfile}\n")
    discover.chmod(0o755)
    state = tmp_path / "state.json"
    log = tmp_path / "train.log"
    ckpt = tmp_path / "ckpts"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update({"HVDTPU_TEST_STATE": str(state), "HVDTPU_TEST_LOG": str(log),
                "HVDTPU_TEST_CKPT": str(ckpt), "HVDTPU_TEST_KILL": "1",
                "HVDTPU_TEST_TOTAL": "24", "HVDTPU_TEST_STEP_DELAY": "0.3"})
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "4",
         "--min-np", "2", "--max-np", "4",
         "--host-discovery-script", str(discover), "--",
         sys.executable,
         os.path.join(REPO, "tests", "mp_elastic_ckpt_worker.py")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)
    try:
        # After the shrink incarnation (np=2) commits a few steps, offer a
        # fresh host so the growth watcher fires.
        deadline = time.time() + 180
        grown = False
        while time.time() < deadline and not grown:
            if log.exists():
                lines = log.read_text().splitlines()
                if any(ln.startswith("STEP rank=0 size=2 step=6")
                       for ln in lines):
                    hostsfile.write_text(
                        f"localhost:2\n127.0.0.1:2\n{my_ip}:2\n")
                    grown = True
            time.sleep(0.5)
        # 600s: the grow/shrink circle spawns 4 workers with fresh jax
        # compiles each resize; on a 2-core rig running right after the
        # full unit stage, 300s was observed marginal (it passes in ~90s
        # standalone) — the generous bound still catches real hangs.
        out, _ = proc.communicate(timeout=600)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out
    lines = log.read_text().splitlines()
    assert "START rank=0 size=4 resume_step=0" in lines, lines
    assert "CRASH rank=2 step=4" in lines, lines
    # Shrink leg: np=2 restored from the step-4 orbax checkpoint.
    assert "START rank=0 size=2 resume_step=4" in lines, lines
    # Grow leg: back at np=4, restored from a later checkpoint.
    grow_starts = [ln for ln in lines if ln.startswith(
        "START rank=0 size=4 resume_step=") and
        int(ln.rsplit("=", 1)[1]) > 4]
    assert grow_starts, lines
    assert any(ln.startswith("DONE rank=0 size=4 step=24")
               for ln in lines), lines

    # Loss continuity: every logged loss equals the uninterrupted oracle.
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    rng = np.random.RandomState(7)
    X = jnp.asarray(rng.randn(32, 4), jnp.float32)
    y = jnp.asarray(rng.randn(32, 1), jnp.float32)
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    params = {"w1": jax.random.normal(k1, (4, 8)) * 0.5,
              "b1": jnp.zeros((8,)),
              "w2": jax.random.normal(k2, (8, 1)) * 0.5,
              "b2": jnp.zeros((1,))}

    def loss_fn(p):
        h = jnp.tanh(X @ p["w1"] + p["b1"])
        return jnp.mean(((h @ p["w2"] + p["b2"]) - y) ** 2)

    tx = optax.adam(5e-2)
    opt_state = tx.init(params)
    oracle = []
    for _ in range(24):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        oracle.append(float(loss))
    logged = {}
    for ln in lines:
        if ln.startswith("STEP rank=0 "):
            fields = dict(f.split("=") for f in ln.split()[1:])
            logged[int(fields["step"])] = float(fields["loss"])
    assert logged, lines
    for step, loss in sorted(logged.items()):
        assert abs(loss - oracle[step]) < 1e-5, (
            f"step {step}: logged {loss} vs oracle {oracle[step]} — "
            "state did not survive the restart")


def test_host_hash_stable_and_overridable(monkeypatch):
    from horovod_tpu.runner.hosts import host_hash
    a = host_hash()
    assert a == host_hash() and len(a) == 32
    monkeypatch.setenv("HOROVOD_HOSTNAME", "shared-fs-node")
    b = host_hash()
    assert b != a
    assert host_hash(salt="split") != b


@pytest.mark.integration
@pytest.mark.slow
def test_chaos_recovery_scenario_harness():
    """Acceptance (the chaos-recovery CI job, wrapped): the np=4
    elastic scenario — injected rank death + flaky KV + delayed
    negotiation, driver blacklists and relaunches, results stay
    correct, a flight-recorder bundle names the injected fault — plus
    the determinism scenario (same seed => identical fault sequence).
    The serving scenario runs separately in the CI job (it needs a
    fresh process for hvd.init at np=1); its logic is tier-1-covered
    in test_chaos.py.  slow-marked: several runner startups."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    for scenario in ("elastic", "determinism"):
        res = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.chaos.run",
             "--scenario", scenario],
            capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
        assert res.returncode == 0, res.stdout + res.stderr
        assert "CHAOS-OK" in res.stdout, res.stdout


@pytest.mark.integration
@pytest.mark.slow
def test_router_failover_scenario_harness():
    """Acceptance (the router-failover CI job, wrapped): two serving
    replicas behind the front-door router, an injected serving_step
    death kills one mid-stream, and every in-flight request completes
    token-identical on the survivor while /healthz and the router
    health gauge flip.  slow-marked: two full serving-worker
    startups."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("HVDTPU_FAULTS", None)
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.chaos.run",
         "--scenario", "router"],
        capture_output=True, text=True, timeout=480, env=env, cwd=REPO)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "CHAOS-ROUTER-OK" in res.stdout, res.stdout
    assert "CHAOS-OK" in res.stdout, res.stdout


@pytest.mark.integration
@pytest.mark.slow
def test_autoscale_recovery_scenario_harness():
    """Acceptance (the autoscale-recovery CI job, wrapped): the np=4
    expert-parallel MoE job under the closed-loop autoscaler — an
    injected rank death shrinks it to np=2 (blacklist), an SLO burn
    load spike holds scale-up pressure, and the controller grows it
    back to np=4 when the cooldown lapses, with exact state continuity
    and every decision on the metric/flight-recorder record.
    slow-marked: three full runner rounds plus a real 12s blacklist
    cooldown (~60-90s wall)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("HVDTPU_FAULTS", None)
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.chaos.run",
         "--scenario", "autoscale"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "CHAOS-AUTOSCALE-OK" in res.stdout, res.stdout
    assert "CHAOS-OK" in res.stdout, res.stdout


@pytest.mark.integration
@pytest.mark.slow
def test_disagg_recovery_scenario_harness():
    """Acceptance (the disagg-recovery CI job, wrapped): np=4 replica
    workers pool-tagged 2 prefill + 2 decode behind the DisaggRouter,
    an injected mig_export death kills a prefill replica mid-migration
    (K chunk published, manifest not), and every request completes
    token-identical via durable-point replay on the pool sibling while
    the decode pool's eligibility gauge never dips.  slow-marked: four
    full serving-worker startups."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("HVDTPU_FAULTS", None)
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.chaos.run",
         "--scenario", "disagg"],
        capture_output=True, text=True, timeout=480, env=env, cwd=REPO)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "CHAOS-DISAGG-OK" in res.stdout, res.stdout
    assert "CHAOS-OK" in res.stdout, res.stdout
