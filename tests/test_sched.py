"""Collective schedule IR (ops/sched): lowering, signatures, resolution,
executor parity, in-jit entry points.

The load-bearing property throughout: decomposed and monolithic
allreduce are BIT-exact equals — fp32 because psum and
psum_scatter+all_gather perform the identical per-element float ops on
this backend, quantized modes by construction (chunk boundaries land on
the monolithic kernel's block boundaries, narrow-accumulator sums are
order-independent).  Parity over the real negotiated transport lives in
tests/mp_sched_worker.py / test_runner.py.
"""

import dataclasses
import json

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.ops import sched
from horovod_tpu.ops.sched import ir

N = 8


@pytest.fixture
def sched_cfg():
    """Flip the engine default to decomposed for one test, restore after."""
    cfg = hvd.global_state().config
    old = (cfg.sched_mode, cfg.sched_chunks, cfg.quant_min_bytes)
    yield cfg
    cfg.sched_mode, cfg.sched_chunks, cfg.quant_min_bytes = old


# ---------------------------------------------------------------------------
# IR + lowering
# ---------------------------------------------------------------------------

def test_schedule_signature_stable_and_deterministic():
    a = sched.lower_allreduce(4096, 8, op_average=True, mode="fp32",
                              chunks=4, axis="hvd")
    b = sched.lower_allreduce(4096, 8, op_average=True, mode="fp32",
                              chunks=4, axis="hvd")
    assert a.signature() == b.signature()
    assert a.descriptor == "rs_ag:4"
    # Different lowering inputs -> different signatures.
    c = sched.lower_allreduce(4096, 8, op_average=True, mode="int8",
                              chunks=4, axis="hvd")
    assert c.signature() != a.signature()
    assert "int8" in c.signature()
    d = sched.lower_allreduce(4096, 8, op_average=True, mode="fp32",
                              chunks=2, axis="hvd")
    assert d.signature() != a.signature()


def test_lowered_quant_schedule_has_encode_decode_steps():
    s = sched.lower_allreduce(100000, 8, op_average=True, mode="int8",
                              chunks=2, axis="hvd", block=512)
    kinds = [st.kind for st in s.steps]
    for k in ("chunk", "encode", "reduce_scatter", "combine",
              "all_gather", "decode", "concat"):
        assert k in kinds, kinds
    # fp32 SUM has no compute step at all (nothing to combine).
    s2 = sched.lower_allreduce(4096, 8, op_average=False, mode="fp32",
                               chunks=2, axis="hvd")
    assert "combine" not in [st.kind for st in s2.steps]
    assert "encode" not in [st.kind for st in s2.steps]


def test_interleaved_order_overlaps_comm_with_compute():
    """Every chunk's reduce-scatter must be dispatched before any chunk's
    combine — the property the executor's overlap window rests on."""
    s = sched.lower_allreduce(8192, 8, op_average=True, mode="fp32",
                              chunks=4, axis="hvd")
    order = [(st.kind, st.chunk) for st in s.interleaved_order()]
    last_rs = max(i for i, (k, _) in enumerate(order)
                  if k == "reduce_scatter")
    first_cb = min(i for i, (k, _) in enumerate(order) if k == "combine")
    assert last_rs < first_cb, order
    # And per chunk, the pipeline order holds.
    for c in range(4):
        idx = {k: i for i, (k, ch) in enumerate(order) if ch == c}
        assert idx["reduce_scatter"] < idx["combine"] < idx["all_gather"]


@pytest.mark.parametrize("avg,mode", [(False, "fp32"), (True, "fp32"),
                                      (True, "int8"), (False, "fp8")])
def test_interleaved_order_matches_executor_walk(avg, mode):
    """The executor's hand-sorted dispatch-unit order must equal
    interleaved_order projected onto rs/combine/ag — the equivalence the
    walk's comment in executor.py cites.  The fp32 SUM case is the
    regression: its all_gathers become ready while later reduce-scatters
    are still pending, and a plain COMM-first priority would serialize
    the walk into RS(c), AG(c) pairs with zero overlap window."""
    s = sched.lower_allreduce(16384, 4, op_average=avg, mode=mode,
                              chunks=4, axis="hvd")
    has_combine = mode in ("int8", "fp8") or avg
    executor_order = [(u, c) for c in range(s.chunks)
                      for u in ("rs", "combine", "ag")
                      if u != "combine" or has_combine]
    executor_order.sort(key=lambda uc: (0 if uc[0] == "rs" else 1, uc[1],
                                        0 if uc[0] == "combine" else 1))
    unit = {"reduce_scatter": "rs", "combine": "combine",
            "all_gather": "ag"}
    ir_order = [(unit[st.kind], st.chunk)
                for st in s.interleaved_order() if st.kind in unit]
    assert ir_order == executor_order, (mode, avg, ir_order)
    last_rs = max(i for i, (u, _) in enumerate(ir_order) if u == "rs")
    first_post = min(i for i, (u, _) in enumerate(ir_order) if u != "rs")
    assert last_rs < first_post, ir_order


def test_schedule_validation_rejects_malformed():
    with pytest.raises(ir.ScheduleError):
        ir.Schedule(name="x", steps=(
            ir.Step(uid=0, kind="nonsense"),), chunks=1, mode="fp32")
    with pytest.raises(ir.ScheduleError):  # dangling/forward dep
        ir.Schedule(name="x", steps=(
            ir.Step(uid=0, kind="reduce_scatter", deps=(1,)),
            ir.Step(uid=1, kind="all_gather"),), chunks=1, mode="fp32")
    with pytest.raises(ir.ScheduleError):  # duplicate uid
        ir.Schedule(name="x", steps=(
            ir.Step(uid=0, kind="barrier"),
            ir.Step(uid=0, kind="barrier"),), chunks=1, mode="fp32")


def test_chunk_layout_alignment_and_degradation():
    # fp32: units of n; spread deterministically, covers >= numel.
    lay = sched.chunk_layout(1000, 8, 4, "fp32", 512)
    assert sum(lay) >= 1000 and all(l % 8 == 0 for l in lay)
    assert lay == sched.chunk_layout(1000, 8, 4, "fp32", 512)
    # quant: units of n*block, so shard boundaries land on the SAME block
    # boundaries the monolithic kernel pads to (bit-exactness invariant).
    layq = sched.chunk_layout(100000, 8, 2, "int8", 512)
    assert all(l % (8 * 512) == 0 for l in layq)
    from horovod_tpu.ops.reduction import _padded_len
    assert sum(layq) == _padded_len(100000, 8, 512)
    # Tiny payload: degrades below the requested chunk count (one unit
    # per chunk at most; a sub-unit payload gets exactly one chunk).
    assert sched.chunk_layout(10, 8, 4, "fp32", 512) == [8, 8]
    assert len(sched.chunk_layout(7, 8, 4, "fp32", 512)) == 1


def test_parse_descriptor():
    assert sched.parse_descriptor("rs_ag:4") == 4
    assert sched.parse_descriptor("rs_ag:0") is None
    assert sched.parse_descriptor("banana") is None
    assert sched.parse_descriptor("") is None
    assert sched.descriptor(2) == "rs_ag:2"


def test_parse_compiled_descriptor():
    assert sched.parse_compiled_descriptor("compiled:rs_ag:4") == 4
    assert sched.parse_compiled_descriptor("compiled:rs_ag:0") is None
    assert sched.parse_compiled_descriptor("rs_ag:4") is None
    assert sched.parse_compiled_descriptor("compiled:hier:2:2") is None
    assert sched.parse_compiled_descriptor("") is None
    assert sched.compiled_descriptor(2) == "compiled:rs_ag:2"
    assert sched.known_descriptor("compiled:rs_ag:2")
    # The dispatched parser must NOT claim compiled descriptors.
    assert sched.parse_descriptor("compiled:rs_ag:4") is None


def test_resolve_schedule_gates(sched_cfg):
    from horovod_tpu.ops.collectives import ReduceOp
    cfg = sched_cfg
    cfg.sched_mode, cfg.sched_chunks = "decomposed", 4
    ok = dict(verb="allreduce", op=ReduceOp.AVERAGE, dtype=np.float32,
              nbytes=1 << 20, cfg=cfg, n=8, mode="fp32")

    def res(**kw):
        a = {**ok, **kw}
        return sched.resolve_schedule(a.pop("requested", ""), a["verb"],
                                      a["op"], a["dtype"], a["nbytes"],
                                      a["cfg"], a["n"], a["mode"])
    assert res() == "rs_ag:4"
    assert res(requested="monolithic") == ""
    assert res(requested="rs_ag:2") == "rs_ag:2"
    assert res(verb="allgather") == ""
    assert res(op=ReduceOp.MAX) == ""
    assert res(op=ReduceOp.ADASUM) == ""
    assert res(dtype=np.int32) == ""
    assert res(n=1) == ""
    assert res(nbytes=16) == ""          # too small to cut into 2 chunks
    # Cast wire modes keep the single-psum shape: decomposing them would
    # either re-round the combined shard (diverging from monolithic) or
    # gather at 4 bytes while claiming 2-byte savings.  The executor
    # refuses them outright as the backstop.
    assert res(mode="bf16") == ""
    assert res(mode="fp16") == ""
    from horovod_tpu.ops.sched import executor as SE
    with pytest.raises(ValueError, match="cast wire mode"):
        SE.execute_allreduce(
            [hvd.per_rank([np.ones((64,), np.float32)] * N)], hvd.Sum,
            descriptor="rs_ag:2", precision="bf16")
    with pytest.raises(ValueError):
        res(requested="bogus")
    # Hierarchical flag composes with decomposition.  Single-controller
    # topology detection sees local_size == world size (no tier), so the
    # flag alone keeps the flat descriptor; a valid explicit split
    # upgrades decomposed requests to the chunked+tiered family.
    cfg.hierarchical_allreduce = True
    old_ls = cfg.hierarchical_local_size
    try:
        assert res() == "rs_ag:4"              # invalid split -> flat
        cfg.hierarchical_local_size = 4
        assert res() == "hier:4:4"
        assert res(requested="rs_ag:2") == "hier:4:2"   # upgrade
        assert res(requested="monolithic") == ""  # unchunked kernel path
        # Quantized cross hop tightens the size gate to block units.
        cfg.hierarchical_cross_precision = "int8"
        assert res() == "hier:4:4"
        assert res(nbytes=4 * 8 * 512) == ""   # < 2 block-aligned units
        cfg.hierarchical_cross_precision = ""
    finally:
        cfg.hierarchical_allreduce = False
        cfg.hierarchical_local_size = old_ls
    # Explicit hier requests pass through without the flag; an invalid
    # split degrades to the flat descriptor at the same chunk count.
    assert res(requested="hier:4:2") == "hier:4:2"
    assert res(requested="hier:3:2") == "rs_ag:2"   # 8 % 3 != 0
    assert res(requested="hier:8:2") == "rs_ag:2"   # n_local == n
    # Default config: monolithic.
    cfg.sched_mode = "monolithic"
    assert res() == ""


def test_resolve_schedule_compiled(sched_cfg):
    """The compiled mode shares every eligibility gate with decomposed
    (same chunk_layout, same verb/op/dtype/size rules) and differs only
    in the descriptor family it emits — except under a hierarchical
    split, where it deterministically falls back to the DISPATCHED
    ``hier:*`` family (no compiled tiered lowering yet; ISSUE 16)."""
    from horovod_tpu.ops.collectives import ReduceOp
    cfg = sched_cfg
    cfg.sched_mode, cfg.sched_chunks = "compiled", 4
    ok = dict(verb="allreduce", op=ReduceOp.AVERAGE, dtype=np.float32,
              nbytes=1 << 20, cfg=cfg, n=8, mode="fp32")

    def res(**kw):
        a = {**ok, **kw}
        return sched.resolve_schedule(a.pop("requested", ""), a["verb"],
                                      a["op"], a["dtype"], a["nbytes"],
                                      a["cfg"], a["n"], a["mode"])
    assert res() == "compiled:rs_ag:4"
    assert res(requested="compiled") == "compiled:rs_ag:4"
    assert res(requested="compiled:rs_ag:2") == "compiled:rs_ag:2"
    # Explicit requests for the other backends still win per call.
    assert res(requested="monolithic") == ""
    assert res(requested="rs_ag:2") == "rs_ag:2"
    # Identical gates to decomposed.
    assert res(verb="allgather") == ""
    assert res(op=ReduceOp.MAX) == ""
    assert res(dtype=np.int32) == ""
    assert res(n=1) == ""
    assert res(nbytes=16) == ""
    assert res(mode="bf16") == ""
    assert res(mode="fp16") == ""
    # Hierarchical split: deterministic fallback to the dispatched
    # chunked+tiered family at the SAME chunk count (logged once).
    cfg.hierarchical_allreduce = True
    old_ls = cfg.hierarchical_local_size
    try:
        cfg.hierarchical_local_size = 4
        assert res() == "hier:4:4"
        assert res(requested="compiled:rs_ag:2") == "hier:4:2"
    finally:
        cfg.hierarchical_allreduce = False
        cfg.hierarchical_local_size = old_ls
    # Without the split the compiled family survives the flag.
    assert res() == "compiled:rs_ag:4"


# ---------------------------------------------------------------------------
# Executor parity (single-controller; negotiated-transport parity is the
# mp worker's job)
# ---------------------------------------------------------------------------

def _parts(numel, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randn(numel).astype(np.float32) for _ in range(N)]


def test_decomposed_bit_exact_fp32(sched_cfg):
    parts = _parts(5000)
    x = hvd.per_rank(parts)
    ref = hvd.to_numpy(hvd.allreduce(x, hvd.Average))
    sched_cfg.sched_mode, sched_cfg.sched_chunks = "decomposed", 4
    got = hvd.to_numpy(hvd.allreduce(x, hvd.Average))
    assert np.array_equal(ref, got)          # BIT-exact, not allclose
    # SUM too (no combine step in the schedule).
    sched_cfg.sched_mode = "monolithic"
    ref_s = hvd.to_numpy(hvd.allreduce(x, hvd.Sum))
    sched_cfg.sched_mode = "decomposed"
    got_s = hvd.to_numpy(hvd.allreduce(x, hvd.Sum))
    assert np.array_equal(ref_s, got_s)


@pytest.mark.parametrize("mode", ["int8", "fp8"])
def test_decomposed_bit_exact_quantized(sched_cfg, mode):
    """Chunked quantized pipeline == monolithic quantized kernel, bit for
    bit: same block layout, exact narrow-accumulator sums, same per-block
    requantization — chunking must not change a single ulp."""
    sched_cfg.quant_min_bytes = 0
    parts = _parts(100000, seed=3)
    x = hvd.per_rank(parts)
    sched_cfg.sched_mode = "monolithic"
    ref = hvd.to_numpy(hvd.allreduce(x, hvd.Average, compression=mode))
    sched_cfg.sched_mode, sched_cfg.sched_chunks = "decomposed", 3
    got = hvd.to_numpy(hvd.allreduce(x, hvd.Average, compression=mode))
    assert np.array_equal(ref, got)
    # And the quantized path really ran (lossy vs exact numpy).
    exact = np.stack(parts).mean(0)
    assert np.abs(got - exact).max() > 0


def test_decomposed_grouped_and_prepost_scale(sched_cfg):
    sched_cfg.sched_mode, sched_cfg.sched_chunks = "decomposed", 2
    xs = [hvd.per_rank([np.full((97,), float(r + i), np.float32)
                        for r in range(N)]) for i in range(3)]
    outs = hvd.grouped_allreduce(xs, hvd.Sum)
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(
            hvd.to_numpy(o), np.full((97,), sum(range(N)) + N * i))
    # prescale/postscale ride the rs/ag phases.
    from horovod_tpu.ops import collectives as C
    x = hvd.per_rank(_parts(4096, seed=5))
    sched_cfg.sched_mode = "monolithic"
    ref = hvd.to_numpy(C.allreduce(x, hvd.Sum, prescale_factor=0.5,
                                   postscale_factor=2.0))
    sched_cfg.sched_mode = "decomposed"
    got = hvd.to_numpy(C.allreduce(x, hvd.Sum, prescale_factor=0.5,
                                   postscale_factor=2.0))
    assert np.array_equal(ref, got)


def test_decomposed_overlap_gauge_set(sched_cfg):
    from horovod_tpu.ops.sched.executor import _m_overlap, _m_sched
    sched_cfg.sched_mode, sched_cfg.sched_chunks = "decomposed", 4
    before = _m_sched.labels(schedule="rs_ag:4").value
    x = hvd.per_rank(_parts(8192, seed=7))
    hvd.to_numpy(hvd.allreduce(x, hvd.Average))
    assert _m_sched.labels(schedule="rs_ag:4").value == before + 1
    frac = _m_overlap.value
    assert 0.0 <= frac <= 1.0
    # With >= 2 chunks dispatched interleaved there is always a nonzero
    # window where a chunk's comm is in flight during another's compute.
    assert frac > 0.0


def test_overlap_fraction_math():
    from horovod_tpu.ops.sched.executor import _overlap_fraction
    assert _overlap_fraction([(0, 10)], [(2, 4)]) == pytest.approx(0.2)
    assert _overlap_fraction([(0, 10)], []) == 0.0
    assert _overlap_fraction([], [(0, 1)]) == 0.0
    assert _overlap_fraction([(0, 2), (4, 6)],
                             [(1, 5)]) == pytest.approx(0.5)
    # Overlapping compute windows count their union, not twice.
    assert _overlap_fraction([(0, 10)],
                             [(2, 4), (2, 4)]) == pytest.approx(0.2)
    assert _overlap_fraction([(0, 10)],
                             [(2, 5), (3, 6)]) == pytest.approx(0.4)


# ---------------------------------------------------------------------------
# Compiled single-program backend (ops/sched/compiled)
# ---------------------------------------------------------------------------

def test_compiled_bit_exact_fp32(sched_cfg):
    """One jitted GSPMD program == monolithic psum, bit for bit: the
    compiled kernel inlines the executor's fp32 phase builders, and on
    this backend psum and psum_scatter+all_gather share per-element
    float-op association (the same property the decomposed test pins)."""
    parts = _parts(5000, seed=21)
    x = hvd.per_rank(parts)
    ref = hvd.to_numpy(hvd.allreduce(x, hvd.Average))
    sched_cfg.sched_mode, sched_cfg.sched_chunks = "compiled", 4
    got = hvd.to_numpy(hvd.allreduce(x, hvd.Average))
    assert np.array_equal(ref, got)          # BIT-exact, not allclose
    sched_cfg.sched_mode = "monolithic"
    ref_s = hvd.to_numpy(hvd.allreduce(x, hvd.Sum))
    sched_cfg.sched_mode = "compiled"
    got_s = hvd.to_numpy(hvd.allreduce(x, hvd.Sum))
    assert np.array_equal(ref_s, got_s)
    # And against the dispatched decomposition at the same chunk count.
    sched_cfg.sched_mode = "decomposed"
    deco = hvd.to_numpy(hvd.allreduce(x, hvd.Average))
    assert np.array_equal(got, deco)


@pytest.mark.parametrize("mode", ["int8", "fp8"])
def test_compiled_bit_exact_quantized(sched_cfg, mode):
    """Quantized compiled program == monolithic quantized kernel, bit
    for bit: identical n*block chunk boundaries, shared-pmax scales,
    exact narrow-accumulator psum_scatter, local requantization."""
    sched_cfg.quant_min_bytes = 0
    parts = _parts(100000, seed=23)
    x = hvd.per_rank(parts)
    sched_cfg.sched_mode = "monolithic"
    ref = hvd.to_numpy(hvd.allreduce(x, hvd.Average, compression=mode))
    sched_cfg.sched_mode, sched_cfg.sched_chunks = "compiled", 3
    got = hvd.to_numpy(hvd.allreduce(x, hvd.Average, compression=mode))
    assert np.array_equal(ref, got)
    # The quantized path really ran (lossy vs exact numpy).
    exact = np.stack(parts).mean(0)
    assert np.abs(got - exact).max() > 0


def test_compiled_grouped_and_prepost_scale(sched_cfg):
    sched_cfg.sched_mode, sched_cfg.sched_chunks = "compiled", 2
    xs = [hvd.per_rank([np.full((97,), float(r + i), np.float32)
                        for r in range(N)]) for i in range(3)]
    outs = hvd.grouped_allreduce(xs, hvd.Sum)
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(
            hvd.to_numpy(o), np.full((97,), sum(range(N)) + N * i))
    from horovod_tpu.ops import collectives as C
    x = hvd.per_rank(_parts(4096, seed=25))
    sched_cfg.sched_mode = "monolithic"
    ref = hvd.to_numpy(C.allreduce(x, hvd.Sum, prescale_factor=0.5,
                                   postscale_factor=2.0))
    sched_cfg.sched_mode = "compiled"
    got = hvd.to_numpy(C.allreduce(x, hvd.Sum, prescale_factor=0.5,
                                   postscale_factor=2.0))
    assert np.array_equal(ref, got)


def test_compiled_counters_and_program_cache(sched_cfg):
    """The contract the CI compiled-parity job asserts at np>1: the
    compiled path takes ONE program dispatch (its own counter moves) and
    ZERO per-chunk executor dispatches; re-dispatching the same schedule
    signature is a cache hit, not a rebuild."""
    from horovod_tpu.ops import collectives as C
    from horovod_tpu.ops.sched.compiled import _m_compiled
    from horovod_tpu.ops.sched.executor import _m_sched
    sched_cfg.sched_mode, sched_cfg.sched_chunks = "compiled", 4
    x = hvd.per_rank(_parts(8192, seed=27))
    before_c = _m_compiled.labels(schedule="compiled:rs_ag:4").value
    before_s = _m_sched.total()
    out1 = hvd.to_numpy(hvd.allreduce(x, hvd.Average))
    assert _m_compiled.labels(
        schedule="compiled:rs_ag:4").value == before_c + 1
    assert _m_sched.total() == before_s      # zero per-chunk dispatches
    # Same signature again: program-cache hit, no new build.
    hits0, miss0 = C._cache.hits, C._cache.misses
    out2 = hvd.to_numpy(hvd.allreduce(x, hvd.Average))
    assert C._cache.misses == miss0
    assert C._cache.hits > hits0
    assert np.array_equal(out1, out2)
    assert _m_sched.total() == before_s


def test_compiled_executor_routes_descriptor(sched_cfg):
    """executor.execute_allreduce hands compiled descriptors to the
    compiled backend — the engine's single dispatch call site never
    branches on the family itself."""
    from horovod_tpu.ops.sched import executor as SE
    x = hvd.per_rank(_parts(4096, seed=29))
    ref = hvd.to_numpy(hvd.allreduce(x, hvd.Average))
    out = SE.execute_allreduce([x], hvd.Average,
                               descriptor="compiled:rs_ag:2")
    assert np.array_equal(ref, hvd.to_numpy(out[0]))


def test_compiled_rejects_cast_modes_and_unknown_descriptors():
    from horovod_tpu.ops.sched import compiled as CP
    x = hvd.per_rank([np.ones((64,), np.float32)] * N)
    with pytest.raises(ValueError, match="cast wire mode"):
        CP.execute_allreduce([x], hvd.Sum, descriptor="compiled:rs_ag:2",
                             precision="bf16")
    with pytest.raises(ValueError, match="unknown compiled"):
        CP.execute_allreduce([x], hvd.Sum, descriptor="rs_ag:2")


def test_perfmodel_compiled_expectation():
    """The compiled arm keeps the ring's wire bytes but collapses the
    per-chunk dispatch latency: steps == one ring regardless of k."""
    from horovod_tpu.obs import perfmodel as PM
    c = PM.expected_allreduce(1 << 20, 8, chunks=4, compiled=True)
    d = PM.expected_allreduce(1 << 20, 8, chunks=4)
    assert c.schedule == "compiled:rs_ag:4"
    assert d.schedule == "rs_ag:4"
    assert c.wire_bytes == d.wire_bytes
    assert c.steps == 2 * 7 and d.steps == 2 * 7 * 4


# ---------------------------------------------------------------------------
# In-jit entry points
# ---------------------------------------------------------------------------

def test_in_context_overlap_allreduce_parity():
    import jax
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.jaxcompat import shard_map
    mesh = hvd.mesh()
    axis = hvd.global_state().config.dp_axis_name
    x = np.random.RandomState(11).randn(N, 3000).astype(np.float32)

    def mono(v):
        return lax.psum(v[0], axis) / N

    def deco(v):
        return sched.overlap_allreduce(v[0], axis, average=True, chunks=3)

    f1 = jax.jit(shard_map(mono, mesh=mesh, in_specs=P(axis),
                           out_specs=P(), check_vma=False))
    f2 = jax.jit(shard_map(deco, mesh=mesh, in_specs=P(axis),
                           out_specs=P(), check_vma=False))
    assert np.array_equal(np.asarray(f1(x)), np.asarray(f2(x)))
    # Quantized in-context: parity with the reduction-layer convention
    # within the documented shared-scale bound.
    def deco8(v):
        return sched.overlap_allreduce(v[0], axis, average=True,
                                       mode="int8", chunks=2, block=512)
    f3 = jax.jit(shard_map(deco8, mesh=mesh, in_specs=P(axis),
                           out_specs=P(), check_vma=False))
    got = np.asarray(f3(x))
    exact = x.mean(0)
    gmax = np.abs(x).max()
    assert np.abs(got - exact).max() <= 1.5 * (N + 1) * gmax / 254.0


def test_matmul_reducescatter_parity():
    import jax
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.jaxcompat import shard_map
    mesh = hvd.mesh()
    axis = hvd.global_state().config.dp_axis_name
    rng = np.random.RandomState(13)
    # Row-parallel: contraction dim sharded over the axis (one slice per
    # rank stacked on dim 0), output dim 64 divides n*chunks = 16.
    xs = rng.randn(N, 4, 32).astype(np.float32)     # per-rank [4, 32]
    w = rng.randn(N, 32, 64).astype(np.float32)     # per-rank w slice

    def mono(xv, wv):
        return lax.psum(xv[0] @ wv[0], axis)

    def fused(xv, wv):
        return sched.matmul_reducescatter(xv[0], wv[0], axis, chunks=2)

    f1 = jax.jit(shard_map(mono, mesh=mesh, in_specs=(P(axis), P(axis)),
                           out_specs=P(), check_vma=False))
    f2 = jax.jit(shard_map(fused, mesh=mesh, in_specs=(P(axis), P(axis)),
                           out_specs=P(), check_vma=False))
    assert np.array_equal(np.asarray(f1(xs, w)), np.asarray(f2(xs, w)))
    # Indivisible output dim falls back to the plain psum path.
    w_odd = rng.randn(N, 32, 60).astype(np.float32)
    f3 = jax.jit(shard_map(
        lambda xv, wv: sched.matmul_reducescatter(xv[0], wv[0], axis,
                                                  chunks=7),
        mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=P(),
        check_vma=False))
    f4 = jax.jit(shard_map(mono, mesh=mesh, in_specs=(P(axis), P(axis)),
                           out_specs=P(), check_vma=False))
    assert np.array_equal(np.asarray(f4(xs, w_odd)),
                          np.asarray(f3(xs, w_odd)))


def test_llama_decode_tp_overlap_token_parity():
    """The fused tp matmul + reduce-scatter decode projections must
    produce token-identical generations (the fusion reorders
    communication, not arithmetic)."""
    import dataclasses
    import jax
    import jax.numpy as jnp
    from horovod_tpu.models import llama
    from horovod_tpu.parallel import MeshConfig, build_mesh
    mesh = build_mesh(MeshConfig(pp=2, dp=2, tp=2))
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0), mesh)
    prompt = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(4, 8)), jnp.int32)
    off = llama.generate(params, prompt, cfg, max_new_tokens=4, mesh=mesh)
    on = llama.generate(params, prompt,
                        dataclasses.replace(cfg, decode_tp_overlap=True),
                        max_new_tokens=4, mesh=mesh)
    assert np.array_equal(np.asarray(off), np.asarray(on))


# ---------------------------------------------------------------------------
# Engine integration: meta carries the descriptor; fusion groups split.
# ---------------------------------------------------------------------------

def test_entry_meta_carries_schedule(sched_cfg):
    from horovod_tpu.ops.engine import (TensorTableEntry,
                                        _parse_joinable_meta)
    x = hvd.per_rank([np.ones((4096,), np.float32)] * N)
    e = TensorTableEntry(name="t.sc", verb="allreduce", payload=x,
                         op=hvd.Sum, schedule="rs_ag:4")
    m = json.loads(e.meta())
    assert m["sc"] == "rs_ag:4"
    parsed = _parse_joinable_meta(e.meta())
    assert parsed is not None and parsed["sc"] == "rs_ag:4"
    # Monolithic entries omit the field: default-mode metas stay
    # byte-identical with pre-schedule-IR peers.
    e2 = TensorTableEntry(name="t.sc2", verb="allreduce", payload=x,
                          op=hvd.Sum)
    assert "sc" not in json.loads(e2.meta())
    # Unknown descriptor from a version-skewed peer: skip, don't crash.
    bad = dict(m)
    bad["sc"] = "ring_exchange:9"
    assert _parse_joinable_meta(json.dumps(bad)) is None


def test_fusion_splits_mixed_schedules(sched_cfg):
    from horovod_tpu.ops.engine import TensorTableEntry
    eng = hvd.global_state().engine
    x = hvd.per_rank([np.ones((64,), np.float32)] * N)
    entries = [
        TensorTableEntry(name=f"t.scf.{i}", verb="allreduce", payload=x,
                         op=hvd.Sum, schedule=s)
        for i, s in enumerate(["rs_ag:4", "rs_ag:4", "", "rs_ag:2"])]
    groups = eng._fuse(entries)
    keyed = sorted(tuple(e.schedule for e in g) for g in groups)
    assert keyed == [("",), ("rs_ag:2",), ("rs_ag:4", "rs_ag:4")]


def test_entry_meta_carries_compiled_schedule(sched_cfg):
    """The compiled backend choice rides the SAME ``sc`` negotiation
    field as the dispatched descriptors (wp-style contract): peers
    joining mid-run and version-skewed peers see one vocabulary."""
    from horovod_tpu.ops.engine import (TensorTableEntry,
                                        _parse_joinable_meta)
    x = hvd.per_rank([np.ones((4096,), np.float32)] * N)
    e = TensorTableEntry(name="t.csc", verb="allreduce", payload=x,
                         op=hvd.Sum, schedule="compiled:rs_ag:4")
    m = json.loads(e.meta())
    assert m["sc"] == "compiled:rs_ag:4"
    parsed = _parse_joinable_meta(e.meta())
    assert parsed is not None and parsed["sc"] == "compiled:rs_ag:4"


def test_fusion_splits_compiled_from_dispatched(sched_cfg):
    """Compiled and dispatched entries must never fuse: their payloads
    run through different executables."""
    from horovod_tpu.ops.engine import TensorTableEntry
    eng = hvd.global_state().engine
    x = hvd.per_rank([np.ones((64,), np.float32)] * N)
    entries = [
        TensorTableEntry(name=f"t.cf.{i}", verb="allreduce", payload=x,
                         op=hvd.Sum, schedule=s)
        for i, s in enumerate(
            ["compiled:rs_ag:4", "rs_ag:4", "compiled:rs_ag:4", ""])]
    groups = eng._fuse(entries)
    keyed = sorted(tuple(e.schedule for e in g) for g in groups)
    assert keyed == [("",), ("compiled:rs_ag:4", "compiled:rs_ag:4"),
                     ("rs_ag:4",)]


def test_reconcile_metas_adopts_echoed_common_mode(sched_cfg):
    """Mixed-mode peers: the coordinator echoes the lowest rank's meta
    and every rank adopts its schedule/wire fields BEFORE fusion, so all
    processes execute the same program (collective channel IDs are
    per-executable under jax.distributed — a rank running the compiled
    program against peers walking per-chunk dispatches deadlocks)."""
    from horovod_tpu.ops.engine import TensorTableEntry
    eng = hvd.global_state().engine
    x = hvd.per_rank([np.ones((4096,), np.float32)] * N)
    e = TensorTableEntry(name="t.rm", verb="allreduce", payload=x,
                         op=hvd.Sum, schedule="compiled:rs_ag:4")
    peer = TensorTableEntry(name="t.rm", verb="allreduce", payload=x,
                            op=hvd.Sum, schedule="rs_ag:4",
                            precision="int8")
    eng._reconcile_metas([e], {"t.rm": e}, {"t.rm": peer.meta()})
    assert e.schedule == "rs_ag:4"
    assert e.precision == "int8"
    # Echo of our own meta: no-op.
    e2 = TensorTableEntry(name="t.rm2", verb="allreduce", payload=x,
                          op=hvd.Sum, schedule="compiled:rs_ag:4")
    eng._reconcile_metas([e2], {"t.rm2": e2}, {"t.rm2": e2.meta()})
    assert e2.schedule == "compiled:rs_ag:4"
    # Unparseable meta from a version-skewed peer: skip, don't adopt.
    bad = json.loads(peer.meta())
    bad["sc"] = "ring_exchange:9"
    e3 = TensorTableEntry(name="t.rm3", verb="allreduce", payload=x,
                          op=hvd.Sum, schedule="compiled:rs_ag:4")
    eng._reconcile_metas([e3], {"t.rm3": e3}, {"t.rm3": json.dumps(bad)})
    assert e3.schedule == "compiled:rs_ag:4"
    # The adopted direction also runs dispatched -> compiled.
    e4 = TensorTableEntry(name="t.rm4", verb="allreduce", payload=x,
                          op=hvd.Sum, schedule="rs_ag:4")
    peer4 = TensorTableEntry(name="t.rm4", verb="allreduce", payload=x,
                             op=hvd.Sum, schedule="compiled:rs_ag:4")
    eng._reconcile_metas([e4], {"t.rm4": e4}, {"t.rm4": peer4.meta()})
    assert e4.schedule == "compiled:rs_ag:4"


def test_zero_entry_rebuilds_schedule(sched_cfg):
    """A joined rank must rebuild entries at the SAME schedule (and
    precision) the live ranks resolved, or the per-chunk dispatches
    diverge across processes."""
    eng = hvd.global_state().engine
    meta = {"v": "allreduce", "d": "float32", "s": [N, 4096], "o": "sum",
            "sc": "rs_ag:4"}
    from horovod_tpu.ops.engine import _parse_joinable_meta
    e = eng._zero_entry("t.zj", _parse_joinable_meta(json.dumps(meta)))
    assert e.schedule == "rs_ag:4"
    assert e.precision == ""
    # Compiled descriptors rebuild identically.
    meta["sc"] = "compiled:rs_ag:4"
    e2 = eng._zero_entry("t.zjc", _parse_joinable_meta(json.dumps(meta)))
    assert e2.schedule == "compiled:rs_ag:4"
