"""serving/: paged KV cache, continuous-batching scheduler, engine.

Deterministic CPU tests.  The load-bearing assertion is greedy-token
parity: the engine must reproduce batch ``generate()``'s tokens exactly —
same model math, different cache placement — on same-length batches,
mixed-length workloads, under preemption pressure, through the Pallas
paged kernel, and on a dp/tp mesh.
"""

from __future__ import annotations

from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import llama
from horovod_tpu.parallel import MeshConfig, build_mesh
from horovod_tpu.serving.kv_pager import (KVPager, OutOfBlocks,
                                          PagedKVCache, gather_blocks)
from horovod_tpu.serving.scheduler import Request, Scheduler


@pytest.fixture(scope="module")
def tiny():
    cfg = llama.LlamaConfig.tiny()            # v256 d64 L2 H4 KV2 fp32
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _prompts(rng, lens):
    return [rng.randint(0, 256, size=(n,)).astype(np.int32) for n in lens]


def _generate_oracle(params, cfg, prompt, max_new):
    return np.asarray(llama.generate(
        params, jnp.asarray(prompt[None]), cfg, max_new_tokens=max_new))[0]


# ---------------------------------------------------------------------------
# pager
# ---------------------------------------------------------------------------

def _pager(num_blocks=8, block_size=4):
    return KVPager(PagedKVCache(n_layers=2, num_blocks=num_blocks,
                                block_size=block_size, kv_heads=2,
                                head_dim=8))


def test_pager_allocate_free_invariants():
    p = _pager()
    t1 = p.allocate(1, 7)             # 2 blocks
    t2 = p.allocate(2, 9)             # 3 blocks
    assert len(t1) == 2 and len(t2) == 3
    assert 0 not in t1 + t2, "scratch block 0 must never be handed out"
    assert len(set(t1) & set(t2)) == 0, "no block owned twice"
    p.check_invariants()
    assert p.free_blocks == 7 - 5
    p.release(1)
    assert p.free_blocks == 4
    p.check_invariants()
    # freed blocks are re-usable
    t3 = p.allocate(3, 16)            # 4 blocks
    assert set(t3) & set(t1), "released blocks should be reused"
    p.check_invariants()


def test_pager_oom_and_errors():
    p = _pager(num_blocks=4)          # 3 usable
    p.allocate(1, 8)                  # 2 blocks
    with pytest.raises(OutOfBlocks):
        p.allocate(2, 8)              # needs 2, only 1 free
    # failed allocation must not leak state
    p.check_invariants()
    assert p.free_blocks == 1
    with pytest.raises(ValueError):
        p.allocate(1, 4)              # duplicate id
    with pytest.raises(KeyError):
        p.release(99)                 # foreign free
    p.release(1)
    with pytest.raises(KeyError):
        p.release(1)                  # double free
    p.check_invariants()


def test_pager_extend_and_table_matrix():
    p = _pager()
    p.allocate(1, 4)                  # 1 block
    tbl = p.extend(1, 5)              # crosses into block 2
    assert len(tbl) == 2
    assert p.extend(1, 6) == tbl      # no growth needed
    m = p.table_matrix([1, -1], 4)
    assert m.shape == (2, 4)
    assert list(m[0][:2]) == tbl and list(m[0][2:]) == [0, 0]
    assert list(m[1]) == [0, 0, 0, 0], "inactive rows are all-scratch"


# ---------------------------------------------------------------------------
# scheduler (host-only: no jax)
# ---------------------------------------------------------------------------

def _req(i, n, max_new=4):
    return Request(req_id=i, prompt=np.arange(n, dtype=np.int32),
                   max_new_tokens=max_new)


def test_scheduler_fifo_admission_token_budget():
    p = _pager(num_blocks=64, block_size=4)
    s = Scheduler(p, max_active=8, prefill_token_budget=20)
    for i, n in enumerate([16, 16, 16, 4]):
        s.submit(_req(i, n))
    first = [r.req_id for r in s.admit()]
    # 16 + 16 exceeds the budget after the first; strict FIFO means the
    # short prompt 3 must NOT jump the queue.
    assert first == [0], f"budget admission broke FIFO: {first}"
    assert [r.req_id for r in s.admit()] == [1]


def test_scheduler_single_overbudget_prompt_still_admitted():
    p = _pager(num_blocks=64, block_size=4)
    s = Scheduler(p, max_active=4, prefill_token_budget=8)
    s.submit(_req(0, 100))            # alone and over budget
    assert [r.req_id for r in s.admit()] == [0]


def test_scheduler_blocks_gate_admission_fifo():
    p = _pager(num_blocks=8, block_size=4)   # 7 usable
    s = Scheduler(p, max_active=4, prefill_token_budget=1000)
    s.submit(_req(0, 20))             # needs 6 blocks (20+1 tokens)
    s.submit(_req(1, 4))              # would fit, but FIFO holds it back
    assert [r.req_id for r in s.admit()] == [0]
    assert [r.req_id for r in s.admit()] == [], \
        "head-of-line request must not be bypassed"
    s.finish(s.running[0])
    assert [r.req_id for r in s.admit()] == [1]


def test_scheduler_preemption_requeues_with_progress():
    p = _pager(num_blocks=8, block_size=4)   # 7 usable
    s = Scheduler(p, max_active=2, prefill_token_budget=1000)
    s.submit(_req(0, 8, max_new=20))
    s.submit(_req(1, 8, max_new=20))
    admitted = s.admit()
    assert len(admitted) == 2         # 3 blocks each (8+1 tokens)
    a, b = admitted
    a.generated = [7, 8]
    a.context_len = 10
    b.generated = [9]
    b.context_len = 9
    # grow a until the pool forces preemption of b (the youngest other)
    for n in range(11, 24):
        s.grow(a)
        a.context_len = n
    assert b.state.value == "waiting" and b.preemptions == 1
    assert s.waiting[0] is b, "preempted request re-queues at the FRONT"
    # generated tokens folded into the re-prefill prompt
    assert list(b.prefill_tokens) == list(b.prompt) + [9]


# ---------------------------------------------------------------------------
# engine vs generate(): greedy-token parity
# ---------------------------------------------------------------------------

def test_engine_matches_generate_same_length_batch(tiny):
    cfg, params = tiny
    rng = np.random.RandomState(1)
    P, M = 8, 6
    prompts = rng.randint(0, cfg.vocab_size, size=(3, P)).astype(np.int32)
    ref = np.asarray(llama.generate(
        params, jnp.asarray(prompts), cfg, max_new_tokens=M))
    sess = serving.serve(params, cfg, block_size=4, num_blocks=64,
                         max_active=4)
    futs = [sess.submit(p, M) for p in prompts]
    sess.drain()
    for i, f in enumerate(futs):
        assert list(f.result().full_sequence) == list(ref[i]), \
            f"token mismatch on request {i}"


def test_engine_matches_generate_mixed_lengths(tiny):
    cfg, params = tiny
    rng = np.random.RandomState(2)
    lens = [5, 11, 3, 16, 9]
    mx = [4, 7, 12, 3, 6]
    prompts = _prompts(rng, lens)
    sess = serving.serve(params, cfg, block_size=4, num_blocks=64,
                         max_active=3)
    futs = [sess.submit(p, m) for p, m in zip(prompts, mx)]
    sess.drain()
    for i, f in enumerate(futs):
        ref = _generate_oracle(params, cfg, prompts[i], mx[i])
        assert list(f.result().full_sequence) == list(ref), \
            f"token mismatch on request {i} (len {lens[i]})"


def test_engine_parity_under_preemption_pressure(tiny):
    """A pool too small for the whole workload forces preemptions; the
    re-prefilled continuation must still match generate() exactly."""
    cfg, params = tiny
    rng = np.random.RandomState(3)
    lens = [6, 6, 6]
    mx = [10, 10, 10]
    prompts = _prompts(rng, lens)
    # 11 usable blocks of 2 = 22 token slots; 3 requests need 16+ each.
    sess = serving.serve(params, cfg, block_size=2, num_blocks=12,
                         max_active=3)
    futs = [sess.submit(p, m) for p, m in zip(prompts, mx)]
    sess.drain()
    preemptions = 0
    for i, f in enumerate(futs):
        res = f.result()
        preemptions += res.metrics["preemptions"]
        ref = _generate_oracle(params, cfg, prompts[i], mx[i])
        assert list(res.full_sequence) == list(ref), \
            f"token mismatch on request {i} after preemption"
    assert preemptions > 0, "pool was sized to force preemption"


def test_engine_bucketed_prefill_matches_exact(tiny):
    """Right-padded bucketed prefill must emit the same tokens as
    exact-length compiles (causality makes the padded tail inert)."""
    cfg, params = tiny
    rng = np.random.RandomState(4)
    lens = [3, 5, 9]
    prompts = _prompts(rng, lens)
    sess = serving.serve(params, cfg, block_size=4, num_blocks=64,
                         max_active=3, prefill_buckets=(8, 16))
    futs = [sess.submit(p, 5) for p in prompts]
    sess.drain()
    for i, f in enumerate(futs):
        ref = _generate_oracle(params, cfg, prompts[i], 5)
        assert list(f.result().full_sequence) == list(ref)


def test_engine_paged_flash_kernel_mode(tiny):
    """use_flash="interpret" routes decode attention through the Pallas
    paged kernel (scalar-prefetch block tables); tokens must match the
    XLA gather path bit for bit."""
    cfg, params = tiny
    rng = np.random.RandomState(5)
    prompts = _prompts(rng, [6, 10])
    sess = serving.serve(params, cfg, block_size=8, num_blocks=32,
                         max_active=2, use_flash="interpret")
    futs = [sess.submit(p, 6) for p in prompts]
    sess.drain()
    for i, f in enumerate(futs):
        ref = _generate_oracle(params, cfg, prompts[i], 6)
        assert list(f.result().full_sequence) == list(ref)


def test_paged_attention_kernel_vs_gather_oracle():
    from horovod_tpu.models.llama import _cached_attend
    from horovod_tpu.ops import flash_attention as FA
    rng = np.random.RandomState(0)
    B, H, KV, Dh, NB, BS, C = 3, 8, 2, 64, 16, 8, 4
    q = jnp.asarray(rng.randn(B, H, Dh), jnp.float32)
    kp = jnp.asarray(rng.randn(NB, BS, KV, Dh), jnp.float32)
    vp = jnp.asarray(rng.randn(NB, BS, KV, Dh), jnp.float32)
    tables = jnp.asarray(
        rng.choice(np.arange(1, NB), size=(B * C,),
                   replace=False).reshape(B, C), jnp.int32)
    lengths = jnp.asarray([5, 17, 32], jnp.int32)
    out = FA.paged_attention(q, kp, vp, tables, lengths, interpret=True)
    keys, vals = gather_blocks(kp, tables), gather_blocks(vp, tables)
    mask = (jnp.arange(C * BS)[None, :] < lengths[:, None])[:, None, :]
    ref = _cached_attend(q[:, None], keys, vals, mask,
                         1.0 / np.sqrt(Dh))[:, 0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_engine_on_mesh_matches_generate(tiny):
    """dp=4/tp=2 mesh: pool kv_heads over tp (never replicated), decode
    batch over dp — tokens must match the plain single-device engine and
    generate()."""
    cfg, params = tiny
    mesh = build_mesh(MeshConfig(dp=4, tp=2))
    params_s = jax.device_put(params, llama.param_shardings(cfg, mesh))
    rng = np.random.RandomState(6)
    prompts = _prompts(rng, [7, 4, 12, 9])
    sess = serving.serve(params_s, cfg, mesh=mesh, block_size=4,
                         num_blocks=64, max_active=4)
    futs = [sess.submit(p, 5) for p in prompts]
    sess.drain()
    for i, f in enumerate(futs):
        ref = _generate_oracle(params, cfg, prompts[i], 5)
        assert list(f.result().full_sequence) == list(ref), \
            f"mesh token mismatch on request {i}"


# ---------------------------------------------------------------------------
# streaming, metrics, timeline
# ---------------------------------------------------------------------------

def test_streaming_callback_ordering(tiny):
    cfg, params = tiny
    rng = np.random.RandomState(7)
    prompts = _prompts(rng, [4, 8])
    events: list[tuple[int, int]] = []
    sess = serving.serve(params, cfg, block_size=4, num_blocks=64,
                         max_active=2)
    futs = [sess.submit(p, 6, stream_cb=lambda rid, tok:
                        events.append((rid, tok))) for p in prompts]
    sess.drain()
    for f in futs:
        res = f.result()
        streamed = [t for rid, t in events if rid == res.req_id]
        assert streamed == res.tokens, \
            "per-request stream must be the token sequence, in order"
    # interleaving property: each request's events appear in generation
    # order even when interleaved with the other request's
    first_positions = {}
    for i, (rid, _) in enumerate(events):
        first_positions.setdefault(rid, i)
    assert len(first_positions) == 2


def test_metrics_and_timeline_spans(tiny, tmp_path):
    from horovod_tpu.utils.timeline import Timeline
    cfg, params = tiny
    rng = np.random.RandomState(8)
    path = str(tmp_path / "serving_timeline.json")
    sess = serving.serve(params, cfg, block_size=4, num_blocks=64,
                         max_active=2, timeline=Timeline(path))
    fut = sess.submit(_prompts(rng, [6])[0], 4)
    sess.drain()
    m = fut.result().metrics
    assert m["new_tokens"] == 4
    assert m["queue_wait_s"] >= 0
    assert m["ttft_s"] is not None and m["ttft_s"] >= 0
    assert m["decode_tokens_per_s"] is None or m["decode_tokens_per_s"] > 0
    sess.close()
    text = open(path).read()
    assert "QUEUE" in text and "DECODE" in text and "req0" in text


def test_submit_validation(tiny):
    cfg, params = tiny
    sess = serving.serve(params, cfg, block_size=4, num_blocks=8,
                         max_active=1)
    with pytest.raises(ValueError, match="empty"):
        sess.submit(np.zeros((0,), np.int32), 4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        sess.submit(np.arange(4, dtype=np.int32), 0)


def test_submit_rejects_prompt_larger_than_pool(tiny):
    """An unfillable prompt must be rejected up front: at the head of the
    strictly-FIFO queue it would otherwise livelock admission forever."""
    cfg, params = tiny
    sess = serving.serve(params, cfg, block_size=4, num_blocks=8,
                         max_active=2)                 # 7 usable = 28 slots
    with pytest.raises(ValueError, match="blocks"):
        sess.submit(np.arange(40, dtype=np.int32) % 100, 4)
    # and a fitting request behind the rejection still works
    fut = sess.submit(np.arange(6, dtype=np.int32), 2)
    sess.drain()
    assert len(fut.result().tokens) == 2


def test_scheduler_fails_unfittable_requeued_request():
    """A preempted request whose folded-in progress no longer fits the
    pool must be FAILED (drained via engine.pop_failed), not left to
    livelock the FIFO head."""
    p = _pager(num_blocks=4, block_size=4)             # 3 usable = 12 slots
    s = Scheduler(p, max_active=2, prefill_token_budget=1000)
    r = _req(0, 4, max_new=30)
    s.submit(r)
    r.prefill_tokens = np.arange(20, dtype=np.int32)   # preemption fold
    assert s.admit() == []
    assert s.waiting == deque() or not s.waiting
    assert len(s.failed) == 1 and s.failed[0][0] is r
    assert isinstance(s.failed[0][1], OutOfBlocks)


def test_background_thread_failure_sets_future_exception(tiny):
    """A request that outgrows the pool while running ALONE raises
    OutOfBlocks in the engine; the background thread must surface it on
    the pending future instead of dying silently."""
    cfg, params = tiny
    # 3 usable blocks = 12 token slots; prompt 4 + max_new 12 overflows.
    sess = serving.serve(params, cfg, block_size=4, num_blocks=4,
                         max_active=1)
    fut = sess.submit(np.arange(4, dtype=np.int32), 12)
    sess.start()
    with pytest.raises(OutOfBlocks):
        fut.result(timeout=120)
    sess.close()


def test_eos_token_stops_early(tiny):
    cfg, params = tiny
    rng = np.random.RandomState(9)
    prompt = _prompts(rng, [6])[0]
    ref = _generate_oracle(params, cfg, prompt, 8)
    eos = int(ref[len(prompt) + 2])   # the 3rd generated token
    sess = serving.serve(params, cfg, block_size=4, num_blocks=64,
                         max_active=1)
    fut = sess.submit(prompt, 8, eos_token=eos)
    sess.drain()
    res = fut.result()
    assert res.tokens == list(ref[len(prompt):len(prompt) + 3]), \
        "generation must stop AT the eos token"
