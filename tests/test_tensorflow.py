"""TensorFlow binding tests.

Mirrors † ``test/parallel/test_tensorflow.py`` (allreduce semantics across
dtypes, DistributedGradientTape gradient averaging) and
† ``test_tensorflow2_keras.py`` (DistributedOptimizer inside model.fit).
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import horovod_tpu as hvd  # noqa: E402
import horovod_tpu.tensorflow as hvd_tf  # noqa: E402

N = 8  # fake devices; single process drives all ranks with the same tensor


def test_tf_allreduce_sum_tiles_local_ranks():
    t = tf.constant([1.0, 2.0, 3.0])
    out = hvd_tf.allreduce(t, hvd.Sum)
    assert np.allclose(out.numpy(), np.array([1, 2, 3], np.float32) * N)


def test_tf_allreduce_average_identity():
    t = tf.constant(np.random.RandomState(0).randn(3, 4).astype(np.float32))
    out = hvd_tf.allreduce(t, hvd.Average)
    assert np.allclose(out.numpy(), t.numpy(), atol=1e-6)


def test_tf_allreduce_inside_tf_function():
    @tf.function
    def fn(x):
        return hvd_tf.allreduce(x, hvd.Sum)

    out = fn(tf.constant([2.0, 4.0]))
    assert np.allclose(out.numpy(), [2.0 * N, 4.0 * N])


def test_tf_broadcast_and_allgather():
    t = tf.constant([[5, 6]], dtype=tf.int32)
    assert np.array_equal(hvd_tf.broadcast(t, root_rank=2).numpy(), [[5, 6]])
    gathered = hvd_tf.allgather(t)
    assert gathered.shape == (N, 2)


def test_tf_async_roundtrip():
    h = hvd_tf.allreduce_async(tf.ones((4,)), hvd.Sum, name="tf.async")
    out = hvd_tf.synchronize(h)
    assert np.allclose(out.numpy(), np.full((4,), float(N)))


def test_tf_broadcast_variables_inplace():
    v1 = tf.Variable([1.0, 2.0])
    v2 = tf.Variable([[3.0]])
    hvd_tf.broadcast_variables([v1, v2], root_rank=0)
    assert np.allclose(v1.numpy(), [1.0, 2.0])
    assert np.allclose(v2.numpy(), [[3.0]])


def test_tf_distributed_gradient_tape_matches_plain():
    x = tf.constant(np.random.RandomState(1).randn(8, 4).astype(np.float32))
    w = tf.Variable(np.random.RandomState(2).randn(4, 1).astype(np.float32))

    with tf.GradientTape() as plain_tape:
        loss = tf.reduce_mean(tf.square(x @ w))
    plain_grad = plain_tape.gradient(loss, [w])[0]

    with hvd_tf.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_mean(tf.square(x @ w))
    dist_grad = tape.gradient(loss, [w])[0]

    # Average over identical ranks == plain gradient.
    assert np.allclose(dist_grad.numpy(), plain_grad.numpy(), atol=1e-5)


def test_tf_gradient_tape_and_broadcast_inside_tf_function():
    # † the reference's documented TF2 pattern: DistributedGradientTape +
    # first-batch broadcast_variables, all inside one @tf.function.
    w = tf.Variable([[1.0], [2.0]])
    x = tf.constant([[3.0, 4.0]])

    @tf.function
    def step(first):
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(x @ w)
        dtape = hvd_tf.DistributedGradientTape(tape)
        grads = dtape.gradient(loss, [w])
        if first:
            hvd_tf.broadcast_variables([w], root_rank=0)
        return grads[0]

    g = step(tf.constant(True))
    assert np.allclose(g.numpy(), [[3.0], [4.0]])
    assert np.allclose(w.numpy(), [[1.0], [2.0]])


def test_tf_gradient_tape_none_grads_pass_through():
    w = tf.Variable([1.0])
    unused = tf.Variable([2.0])
    with hvd_tf.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_sum(w * 3.0)
    grads = tape.gradient(loss, [w, unused])
    assert grads[1] is None
    assert np.allclose(grads[0].numpy(), [3.0])


def _make_model(seed=0):
    import keras
    keras.utils.set_random_seed(seed)
    return keras.Sequential([
        keras.layers.Input((4,)),
        keras.layers.Dense(3, activation="relu"),
        keras.layers.Dense(1),
    ])


def test_tf_distributed_optimizer_eager_matches_plain():
    import keras
    x = np.random.RandomState(0).randn(16, 4).astype(np.float32)
    y = np.random.RandomState(1).randn(16, 1).astype(np.float32)

    ref = _make_model()
    ref_opt = keras.optimizers.SGD(learning_rate=0.1)
    with tf.GradientTape() as tape:
        loss = tf.reduce_mean(tf.square(ref(x) - y))
    ref_opt.apply_gradients(
        zip(tape.gradient(loss, ref.trainable_variables),
            ref.trainable_variables))

    dist = _make_model()
    opt = hvd_tf.DistributedOptimizer(keras.optimizers.SGD(learning_rate=0.1))
    with tf.GradientTape() as tape:
        loss = tf.reduce_mean(tf.square(dist(x) - y))
    opt.apply_gradients(
        zip(tape.gradient(loss, dist.trainable_variables),
            dist.trainable_variables))

    for a, b in zip(ref.get_weights(), dist.get_weights()):
        assert np.allclose(a, b, atol=1e-5)


def test_tf_distributed_optimizer_model_fit_graph_mode():
    import keras
    x = np.random.RandomState(0).randn(32, 4).astype(np.float32)
    y = np.random.RandomState(1).randn(32, 1).astype(np.float32)

    model = _make_model()
    model.compile(
        optimizer=hvd_tf.DistributedOptimizer(
            keras.optimizers.SGD(learning_rate=0.05)),
        loss="mse")
    before = [w.copy() for w in model.get_weights()]
    hist = model.fit(x, y, batch_size=16, epochs=1, verbose=0)
    after = model.get_weights()
    assert np.isfinite(hist.history["loss"][0])
    assert any(not np.allclose(a, b) for a, b in zip(before, after))


def test_tf_distributed_optimizer_backward_passes_per_step():
    import keras
    x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    y = np.random.RandomState(1).randn(8, 1).astype(np.float32)

    # Reference: one step on the mean of two micro-batch gradients.
    ref = _make_model()
    ref_opt = keras.optimizers.SGD(learning_rate=0.1)
    grads_sum = None
    for sl in (slice(0, 4), slice(4, 8)):
        with tf.GradientTape() as tape:
            loss = tf.reduce_mean(tf.square(ref(x[sl]) - y[sl]))
        gs = tape.gradient(loss, ref.trainable_variables)
        grads_sum = gs if grads_sum is None else [
            a + b for a, b in zip(grads_sum, gs)]
    ref_opt.apply_gradients(
        zip([g / 2 for g in grads_sum], ref.trainable_variables))

    dist = _make_model()
    opt = hvd_tf.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.1), backward_passes_per_step=2)
    for sl in (slice(0, 4), slice(4, 8)):
        with tf.GradientTape() as tape:
            loss = tf.reduce_mean(tf.square(dist(x[sl]) - y[sl]))
        opt.apply_gradients(
            zip(tape.gradient(loss, dist.trainable_variables),
                dist.trainable_variables))

    for a, b in zip(ref.get_weights(), dist.get_weights()):
        assert np.allclose(a, b, atol=1e-5)


def test_tf_keras_module_surface():
    import horovod_tpu.tensorflow.keras as hvd_tfk
    assert hvd_tfk.size() == N
    assert callable(hvd_tfk.DistributedOptimizer)
    assert hvd_tfk.callbacks.BroadcastGlobalVariablesCallback is not None
