"""obs/tracemerge: cross-process trace propagation, clock-aligned
fleet merge, critical-path attribution.

Host-only tests (no jax): the trace plane is stdlib-only by design.
The load-bearing assertions are the acceptance criteria's — parent
adoption keeps one trace_id across processes with the ingress sampling
decision final; a merge over missing ranks / crash-cut timeline tails /
skewed clocks still yields one loadable, per-lane-monotonic Perfetto
JSON with cross-process flow arrows; and the critical-path report names
the dominant (phase, rank) the autoscaler consumes.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from horovod_tpu.obs import REGISTRY
from horovod_tpu.obs import server as obs_server
from horovod_tpu.obs import tracemerge as tm
from horovod_tpu.obs.trace import NULL_SPAN, Tracer
from horovod_tpu.utils.timeline import rank_suffixed


class _KV:
    """In-process KV fake with the client surface the trace plane uses
    (set/get/wait/delete/close)."""

    def __init__(self) -> None:
        self._data: dict = {}
        self._cond = threading.Condition()

    def set(self, key, value):
        with self._cond:
            self._data[key] = bytes(value)
            self._cond.notify_all()

    def get(self, key):
        with self._cond:
            return self._data.get(key)

    def wait(self, key, timeout_ms=10000):
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._cond:
            while key not in self._data:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(f"no {key!r}")
                self._cond.wait(left)
            return self._data[key]

    def delete(self, key):
        with self._cond:
            self._data.pop(key, None)

    def close(self):
        pass


def _finish_trace(tracer, name="req", *, lane=None, parent=None,
                  children=("PREFILL",)):
    """One finished trace on ``tracer``; returns its root span."""
    root = tracer.start_trace(name, lane=lane, parent=parent)
    for ch in children:
        sp = root.child(ch)
        sp.end()
    root.end()
    return root


# ---------------------------------------------------------------------------
# propagation: Span.context() / start_trace(parent=...)
# ---------------------------------------------------------------------------

def test_span_context_carries_the_triple():
    t = Tracer(sample_rate=1.0)
    sp = t.start_trace("req")
    ctx = sp.context()
    assert ctx["trace_id"] == sp.trace_id
    assert ctx["span_id"] == sp.span_id
    assert ctx["sampled"] is True
    json.dumps(ctx)                       # must ride any transport
    sp.end()


def test_parent_adoption_joins_the_remote_trace():
    """The far side of a transport adopts (trace_id, span_id): same
    trace_id, local root parented under the remote span — and the
    adopted root still FINISHES its trace despite a non-None parent."""
    a, b = Tracer(sample_rate=1.0), Tracer(sample_rate=1.0)
    remote = a.start_trace("ingress")
    ctx = json.loads(json.dumps(remote.context()))   # wire roundtrip
    local = b.start_trace("serving.migrated", parent=ctx)
    assert local.trace_id == remote.trace_id
    assert local.parent_id == remote.span_id
    local.end()
    remote.end()
    exp = b.export(remote.trace_id)
    assert exp is not None, "adopted root must finish its trace"
    assert exp["spans"][0]["parent_id"] == remote.span_id


def test_parent_accepts_a_live_span_object():
    t = Tracer(sample_rate=1.0)
    root = t.start_trace("req")
    child_root = t.start_trace("hop", parent=root)
    assert child_root.trace_id == root.trace_id
    child_root.end()
    root.end()


def test_unsampled_context_is_final_no_local_reroll():
    """sampled=False at ingress governs the whole chain: a tracer that
    would sample 100% locally must still return the shared no-op span
    (same object — zero per-request allocation on the unsampled path)."""
    t = Tracer(sample_rate=1.0)
    assert t.start_trace("hop", parent={"sampled": False}) is NULL_SPAN
    assert t.start_trace("hop", parent=NULL_SPAN) is NULL_SPAN
    # NULL_SPAN's own context round-trips the decision.
    assert NULL_SPAN.context() == {"sampled": False}


def test_malformed_parent_degrades_to_local_decision():
    t = Tracer(sample_rate=1.0)
    sp = t.start_trace("req", parent="garbage-from-an-old-manifest")
    assert sp is not NULL_SPAN and sp.parent_id is None
    sp.end()


def test_span_ids_are_salted_per_process():
    """Two tracers' counters both start at 1; the per-process salt keeps
    (trace_id, span_id) unique fleet-wide — what flow stitching keys on."""
    a, b = Tracer(sample_rate=1.0), Tracer(sample_rate=1.0)
    sa, sb = a.start_trace("x"), b.start_trace("x")
    assert sa.span_id.startswith(a._salt + "-")
    assert sb.span_id.startswith(b._salt + "-")
    sa.end(), sb.end()


# ---------------------------------------------------------------------------
# publication + collection over the KV store
# ---------------------------------------------------------------------------

def test_local_blob_roundtrip():
    t = Tracer(sample_rate=1.0)
    _finish_trace(t, lane="req0")
    blob = tm.decode_trace_blob(tm.local_trace_blob(3, pool="decode",
                                                    tracer=t))
    assert blob["rank"] == 3 and blob["pool"] == "decode"
    assert len(blob["traces"]) == 1
    with pytest.raises(ValueError):
        tm.decode_trace_blob(b"[]")


def test_publisher_collector_roundtrip():
    kv = _KV()
    remote = Tracer(sample_rate=1.0)
    r_root = _finish_trace(remote, lane="req-remote")
    pub = tm.TracePublisher(1, pool="prefill", tracer=remote,
                            kv_factory=lambda: kv,
                            echo_poll_s=0.005).start()
    assert pub.publish_now()
    local = Tracer(sample_rate=1.0)
    l_root = _finish_trace(local, lane="req-local")
    col = tm.TraceCollector(own_rank=0, own_pool="router", tracer=local,
                            kv_factory=lambda: kv)
    try:
        merged = col.collect()
    finally:
        col.close()
        pub.stop()
    assert merged["ranks"] == [0, 1]
    tids = {e["args"].get("trace_id") for e in merged["traceEvents"]
            if e.get("ph") == "X"}
    assert {r_root.trace_id, l_root.trace_id} <= tids
    assert merged["report"]["n_traces"] == 2
    json.dumps(merged)                    # one loadable /tracez payload


def test_clock_offset_ping_echo():
    kv = _KV()
    pub = tm.TracePublisher(2, tracer=Tracer(sample_rate=1.0),
                            kv_factory=lambda: kv, interval_s=60,
                            echo_poll_s=0.005).start()
    try:
        off = tm.estimate_clock_offset(kv, 2, timeout_s=2.0)
    finally:
        pub.stop()
    # Same host, same clock: the measured offset is bounded by the echo
    # round trip, far under a second.
    assert off is not None and abs(off) < 5e5, off
    # A rank that never echoes yields None, not a hang/crash.
    assert tm.estimate_clock_offset(kv, 9, attempts=1,
                                    timeout_s=0.05) is None


# ---------------------------------------------------------------------------
# merge robustness
# ---------------------------------------------------------------------------

def _blob(rank, trace_id, spans, *, t_start=100.0, pool=None, tail=()):
    return {"rank": rank, "pool": pool,
            "traces": [{"trace_id": trace_id, "name": "req",
                        "lane": f"req{rank}", "t_start_unix": t_start,
                        "spans": list(spans)}],
            "timeline_tail": list(tail)}


def _span(sid, name, t0, dur, parent=None):
    sp = {"span_id": sid, "name": name, "t_offset_s": t0,
          "duration_s": dur}
    if parent:
        sp["parent_id"] = parent
    return sp


def test_merge_missing_rank_is_partial_not_fatal():
    blobs = {0: _blob(0, "t1", [_span("a-1", "req", 0.0, 1.0)]),
             2: _blob(2, "t2", [_span("c-1", "req", 0.0, 1.0)])}
    merged = tm.merge_fleet_trace(blobs)
    assert merged["ranks"] == [0, 2]
    assert {e["pid"] for e in merged["traceEvents"]
            if e.get("ph") == "X"} == {0, 2}
    json.dumps(merged)


def test_merge_skewed_clocks_stays_per_lane_monotonic():
    """300s of wall-clock skew on rank 1, corrected by its measured
    offset: every lane's events still come out time-sorted and
    non-negative on the collector's axis."""
    skew_us = 300e6
    blobs = {
        0: _blob(0, "t1", [_span("a-1", "INGRESS", 0.0, 0.5),
                           _span("a-2", "QUEUE", 0.5, 0.2, "a-1")]),
        1: _blob(1, "t1", [_span("b-1", "DECODE", 0.0, 0.4, "a-1"),
                           _span("b-2", "DECODE", 0.4, 0.4, "a-1")],
                 t_start=100.1 + skew_us / 1e6),
    }
    merged = tm.merge_fleet_trace(blobs, offsets_us={1: skew_us})
    lanes: dict = {}
    for ev in merged["traceEvents"]:
        if ev.get("ph") == "X":
            assert ev["ts"] >= 0, ev
            lanes.setdefault((ev["pid"], ev["tid"]), []).append(ev["ts"])
    assert lanes, "no slices emitted"
    for ts in lanes.values():
        assert ts == sorted(ts), "lane must be emitted monotonically"
    # Rank 1's slices landed near rank 0's axis, not 300s away.
    r1 = [e for e in merged["traceEvents"]
          if e.get("ph") == "X" and e["pid"] == 1]
    assert all(e["ts"] < 10e6 for e in r1), r1


def test_merge_truncated_timeline_tail(tmp_path):
    """A crash-cut timeline file (no closing bracket) still merges: its
    events rebase through the clock_sync anchor and land in the report's
    busy table."""
    path = os.path.join(str(tmp_path), "tl.r1.json")
    evs = [{"name": "clock_sync", "ph": "M", "pid": 0, "tid": 0,
            "args": {"rank": 1, "epoch_us": 100.0e6}},
           {"name": "thread_name", "ph": "M", "pid": 0, "tid": 7,
            "args": {"name": "allreduce.grad"}},
           {"name": "MPI_ALLREDUCE", "ph": "X", "pid": 0, "tid": 7,
            "ts": 1000.0, "dur": 500.0}]
    with open(path, "w") as fh:                  # crash-cut: no ']'
        fh.write("[\n" + ",\n".join(json.dumps(e) for e in evs) + ",\n")
    blob = tm.decode_trace_blob(tm.local_trace_blob(
        1, tracer=Tracer(sample_rate=1.0), timeline_path=path))
    assert blob["timeline_tail"], "tail must survive the truncation"
    merged = tm.merge_fleet_trace({1: blob})
    rows = [e for e in merged["traceEvents"]
            if e.get("name") == "MPI_ALLREDUCE"]
    assert len(rows) == 1 and rows[0]["pid"] == 1
    report = tm.critical_path_report({1: blob})
    assert report["timeline_busy"][0]["name"] == "MPI_ALLREDUCE"
    assert report["timeline_busy"][0]["rank"] == 1


def test_merge_tail_without_clock_anchor_is_skipped():
    tail = [{"name": "X1", "ph": "X", "pid": 0, "tid": 1,
             "ts": 5.0, "dur": 1.0}]      # no clock_sync: unanchorable
    merged = tm.merge_fleet_trace(
        {0: _blob(0, "t1", [_span("a-1", "req", 0.0, 1.0)], tail=tail)})
    assert not [e for e in merged["traceEvents"] if e.get("name") == "X1"]
    assert [e for e in merged["traceEvents"] if e.get("ph") == "X"]


def test_cross_process_flow_arrows():
    """A span whose parent lives on another rank gets an s→f handoff
    arrow; the arrow never points backward in time."""
    blobs = {
        0: _blob(0, "t1", [_span("a-1", "INGRESS", 0.0, 0.3)],
                 pool="router"),
        1: _blob(1, "t1", [_span("b-1", "serving.migrated",
                                 0.0, 0.5, "a-1")],
                 t_start=100.2, pool="decode"),
    }
    merged = tm.merge_fleet_trace(blobs)
    flows = [e for e in merged["traceEvents"] if e.get("cat") == "trace"]
    starts = [e for e in flows if e["ph"] == "s"]
    finishes = [e for e in flows if e["ph"] == "f"]
    assert len(starts) == 1 and len(finishes) == 1
    s, f = starts[0], finishes[0]
    assert s["id"] == f["id"]
    assert s["pid"] == 0 and f["pid"] == 1, "arrow must cross processes"
    assert f["bp"] == "e"
    assert s["ts"] <= f["ts"]
    pools = {e["args"]["name"] for e in merged["traceEvents"]
             if e.get("name") == "process_name"}
    assert pools == {"rank 0 [router]", "rank 1 [decode]"}


def test_intra_process_edges_get_no_merge_arrows():
    blobs = {0: _blob(0, "t1", [_span("a-1", "req", 0.0, 1.0),
                                _span("a-2", "QUEUE", 0.1, 0.2, "a-1")])}
    merged = tm.merge_fleet_trace(blobs)
    assert not [e for e in merged["traceEvents"]
                if e.get("cat") == "trace"]


# ---------------------------------------------------------------------------
# critical-path attribution
# ---------------------------------------------------------------------------

def _two_rank_trace():
    # root on rank 0 covers [0, 1.0]; its rank-1 DECODE child covers
    # [0.1, 0.9] => self(root)=0.2, self(DECODE)=0.8 (the dominant).
    return {
        0: _blob(0, "t1", [_span("a-1", "disagg.request", 0.0, 1.0)]),
        1: _blob(1, "t1", [_span("b-1", "DECODE", 0.1, 0.8, "a-1")]),
    }


def test_critical_path_names_dominant_phase_and_rank():
    report = tm.critical_path_report(_two_rank_trace())
    assert report["n_traces"] == 1
    assert report["dominant_phase"] == "DECODE"
    assert report["dominant_rank"] == 1
    worst = report["slowest"][0]
    assert worst["n_ranks"] == 2
    assert worst["dominant_self_s"] == pytest.approx(0.8)
    by_phase = {(p["phase"], p["rank"]): p["self_s"]
                for p in worst["phases"]}
    assert by_phase[("disagg.request", 0)] == pytest.approx(0.2)


def test_critical_gauges_export():
    tm.export_critical_gauges(tm.critical_path_report(_two_rank_trace()))
    fam = REGISTRY.get("hvd_trace_critical_phase_seconds")
    assert fam.labels(phase="DECODE", rank="1").value == \
        pytest.approx(0.8)


def test_critical_seconds_feed_autoscale_straggler_signal():
    """A rank owning the majority of the fleet's critical time counts as
    a straggler in the autoscaler's signal distillation; a balanced
    fleet contributes none."""
    from horovod_tpu.autoscale.controller import signals_from_families

    def fams(split):
        return [
            {"name": "horovod_tpu_rank_snapshot_age_seconds",
             "samples": [{"labels": {"rank": "0"}, "value": 0.1},
                         {"labels": {"rank": "1"}, "value": 0.1}]},
            {"name": "hvd_trace_critical_phase_seconds",
             "samples": [{"labels": {"phase": "DECODE", "rank": "1"},
                          "value": split},
                         {"labels": {"phase": "req", "rank": "0"},
                          "value": 1.0 - split}]},
        ]

    assert signals_from_families(fams(0.9), current_np=2,
                                 available_slots=2).stragglers == 1
    assert signals_from_families(fams(0.5), current_np=2,
                                 available_slots=2).stragglers == 0


# ---------------------------------------------------------------------------
# /tracez endpoint + CLI fetch
# ---------------------------------------------------------------------------

def test_tracez_endpoint_and_cli_fetch(tmp_path):
    t = Tracer(sample_rate=1.0)
    _finish_trace(t, lane="req0")
    col = tm.TraceCollector(own_rank=0, tracer=t,
                            kv_factory=lambda: None)
    srv = obs_server.MetricsServer(0, addr="127.0.0.1")
    base = f"http://127.0.0.1:{srv.port}"
    try:
        obs_server.set_trace_provider(col.collect)
        with urllib.request.urlopen(f"{base}/tracez", timeout=5) as r:
            merged = json.loads(r.read().decode())
        assert merged["ranks"] == [0]
        assert any(e.get("ph") == "X" for e in merged["traceEvents"])
        assert "report" in merged

        out = os.path.join(str(tmp_path), "fleet.json")
        assert tm.main(["fetch", base, "-o", out, "--report"]) == 0
        with open(out) as fh:
            assert json.load(fh)["ranks"] == [0]

        # A provider that blows up still answers with a loadable body.
        obs_server.set_trace_provider(
            lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        with urllib.request.urlopen(f"{base}/tracez", timeout=5) as r:
            degraded = json.loads(r.read().decode())
        assert degraded["traceEvents"] == [] and "boom" in degraded["error"]

        # Unarmed => 503, not a hang.
        obs_server.set_trace_provider(None)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/tracez", timeout=5)
        assert ei.value.code == 503
    finally:
        obs_server.set_trace_provider(None)
        srv.close()
        col.close()


def test_fleet_trace_fallback_works_unarmed():
    merged = tm.fleet_trace()
    assert "traceEvents" in merged and "report" in merged


# ---------------------------------------------------------------------------
# per-rank timeline paths (satellite: HVDTPU_TIMELINE under np>1)
# ---------------------------------------------------------------------------

def test_rank_suffixed_paths():
    assert rank_suffixed("/tmp/tl.json", 3, 4) == "/tmp/tl.r3.json"
    assert rank_suffixed("/tmp/tl.json", 0, 4) == "/tmp/tl.r0.json"
    assert rank_suffixed("/tmp/tl.json", 0, 1) == "/tmp/tl.json", \
        "np=1 must keep the bare path"
    assert rank_suffixed("/tmp/trace", 2, 4) == "/tmp/trace.r2"


def test_rank_suffixed_is_inferrable_by_merge():
    from horovod_tpu.utils.timeline import _infer_rank
    assert _infer_rank(rank_suffixed("/tmp/tl.json", 3, 4), [], 0) == 3
