"""Time-series tier (obs/tsdb) + declarative alerting (obs/alerts) +
forecast-fed predictive autoscaling.

All stores here run on synthetic timestamps (ingest/eval take explicit
``now``) and the alert engine on a fake clock, so every lifecycle and
every rate is exactly reproducible.  The HTTP tests stand up a real
``MetricsServer`` and go through ``/query`` / ``/alertz`` — the
acceptance surface — with the process-wide tier armed around them.
"""

import json
import urllib.error
import urllib.parse
import urllib.request

import pytest

from horovod_tpu.autoscale.controller import (
    AutoscaleController,
    signals_from_families,
)
from horovod_tpu.autoscale.policy import PolicyConfig, ScalePolicy, Signals
from horovod_tpu.obs import REGISTRY, alerts, flightrec, server, tsdb
from horovod_tpu.obs.tsdb import QueryError, SeriesStore

T0 = 1_000_000.0


def _gauge_fam(name, value, labels=None):
    return {"name": name, "type": "gauge", "help": "",
            "labelnames": tuple((labels or {}).keys()),
            "samples": [{"labels": dict(labels or {}), "value": value}]}


def _counter_fam(name, value, labels=None):
    fam = _gauge_fam(name, value, labels)
    fam["type"] = "counter"
    return fam


def _hist_fam(name, buckets, total, hsum, labels=None):
    return {"name": name, "type": "histogram", "help": "",
            "labelnames": tuple((labels or {}).keys()),
            "samples": [{"labels": dict(labels or {}),
                         "buckets": buckets + [[float("inf"), total]],
                         "sum": hsum, "count": total}]}


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# rings: bounds + downsample math
# ---------------------------------------------------------------------------

def test_raw_ring_is_bounded_by_retention():
    store = SeriesStore(interval_s=1.0, retention_s=10.0)
    for i in range(500):
        store.ingest([_gauge_fam("g", float(i))], now=T0 + i)
    [(_, ser)] = store.select("g")
    assert len(ser.raw) == store.raw_len == 11
    # oldest raw point slid forward with the window
    assert ser.raw[0][0] == T0 + 500 - 11

def test_store_total_memory_is_bounded_at_default_retention():
    """Acceptance: tsdb memory stays bounded — the retained point count
    never exceeds the analytic cap no matter how long sampling runs."""
    store = SeriesStore()    # default 5s interval / 600s retention
    for i in range(3 * store.raw_len):
        store.ingest([_gauge_fam("g", float(i)),
                      _counter_fam("c_total", float(i))],
                     now=T0 + i * store.interval_s)
    cap = store.max_series * (store.raw_len + store.ds_len + 1)
    assert store.n_points() <= cap
    # and per-series: raw ring exactly at its maxlen, ds ring bounded
    for _, ser in store.select("g") + store.select("c_total"):
        assert len(ser.raw) == store.raw_len
        assert len(ser.ds) <= store.ds_len


def test_series_cap_drops_new_series_not_the_process():
    store = SeriesStore(interval_s=1.0, max_series=4)
    fams = [_gauge_fam("g", 1.0, {"k": str(i)}) for i in range(10)]
    store.ingest(fams, now=T0)
    assert store.n_series() == 4
    # existing series still append fine
    store.ingest(fams, now=T0 + 1)
    assert store.n_series() == 4


def test_downsample_buckets_carry_last_min_max_sum_n():
    store = SeriesStore(interval_s=1.0, retention_s=5.0)
    # two full 60s buckets of a sawtooth, then one point to finalize
    vals = {}
    for i in range(121):
        v = float(i % 7)
        vals[i] = v
        store.ingest([_gauge_fam("g", v)], now=T0 + i)
    [(_, ser)] = store.select("g")
    assert len(ser.ds) >= 1
    t_last, last, vmin, vmax, vsum, n = ser.ds[0]
    lo = [vals[i] for i in range(121)
          if (T0 + i) // 60 == T0 // 60]     # first bucket members
    assert n == len(lo)
    assert vmin == min(lo) and vmax == max(lo)
    assert vsum == sum(lo)
    assert last == lo[-1]


def test_window_spans_merge_downsampled_history_with_raw():
    store = SeriesStore(interval_s=1.0, retention_s=10.0)
    for i in range(300):
        store.ingest([_gauge_fam("g", float(i))], now=T0 + i)
    now = T0 + 299
    # max over 4 minutes: raw holds only the last ~10s, so the answer
    # must come from the downsampled ring
    res = tsdb.eval_expr(store, "max_over_time(g[4m])", now=now)
    assert res["series"][0]["value"] == 299.0
    res = tsdb.eval_expr(store, "min_over_time(g[4m])", now=now)
    assert res["series"][0]["value"] < 290.0   # reached back past raw


# ---------------------------------------------------------------------------
# reset-aware rate
# ---------------------------------------------------------------------------

def test_rate_matches_analytic_value_exactly():
    store = SeriesStore(interval_s=1.0)
    for i, v in enumerate([0.0, 7.0, 14.0, 21.0, 28.0]):
        store.ingest([_counter_fam("c_total", v)], now=T0 + 2 * i)
    res = tsdb.eval_expr(store, "rate(c_total[5m])", now=T0 + 8)
    assert abs(res["series"][0]["value"] - 3.5) < 1e-6

def test_rate_across_counter_reset_is_reset_aware():
    """Acceptance: the post-reset value counts as the increase since the
    restart (Prometheus convention), within 1e-6 of analytic."""
    store = SeriesStore(interval_s=1.0)
    vals = [0.0, 5.0, 10.0, 15.0, 2.0, 7.0, 12.0]   # restart after 15
    for i, v in enumerate(vals):
        store.ingest([_counter_fam("c_total", v)], now=T0 + i)
    analytic = (15.0 + 12.0) / 6.0
    res = tsdb.eval_expr(store, "rate(c_total[10m])", now=T0 + 6)
    assert abs(res["series"][0]["value"] - analytic) < 1e-6
    res = tsdb.eval_expr(store, "increase(c_total[10m])", now=T0 + 6)
    assert abs(res["series"][0]["value"] - 27.0) < 1e-6


def test_rate_functions_need_two_points():
    store = SeriesStore(interval_s=1.0)
    store.ingest([_counter_fam("c_total", 5.0)], now=T0)
    res = tsdb.eval_expr(store, "rate(c_total[1m])", now=T0)
    assert res["series"] == []   # omitted, not an error


# ---------------------------------------------------------------------------
# query language: parse/eval goldens
# ---------------------------------------------------------------------------

def test_parse_expr_goldens():
    p = tsdb.parse_expr('rate(m{pool="decode",rank="1"}[1m])')
    assert p["fn"] == "rate" and p["name"] == "m"
    assert p["matchers"] == {"pool": "decode", "rank": "1"}
    assert p["window_s"] == 60.0
    p = tsdb.parse_expr("avg_over_time(q[90s])")
    assert (p["fn"], p["window_s"]) == ("avg_over_time", 90.0)
    p = tsdb.parse_expr("quantile(0.99, h[5m])")
    assert (p["fn"], p["q"], p["window_s"]) == ("quantile", 0.99, 300.0)
    p = tsdb.parse_expr("forecast(q[2m], 30)")
    assert (p["fn"], p["horizon_s"]) == ("forecast", 30.0)
    p = tsdb.parse_expr('up{job="x"}')
    assert (p["fn"], p["matchers"]) == ("instant", {"job": "x"})

@pytest.mark.parametrize("bad", [
    "", "rate(m)", "m[1m]", "rate(m[1x])", "quantile(m[1m])",
    "quantile(1.5, h[1m])", "nope(m[1m])", 'm{broken=}',
    "forecast(q[1m])",
])
def test_parse_expr_rejects_malformed(bad):
    with pytest.raises(QueryError):
        tsdb.parse_expr(bad)


def test_eval_label_matchers_filter_series():
    store = SeriesStore(interval_s=1.0)
    for rank in ("0", "1"):
        store.ingest([_gauge_fam("q", 10.0 * (int(rank) + 1),
                                 {"rank": rank})], now=T0)
    res = tsdb.eval_expr(store, 'q{rank="1"}', now=T0)
    assert [s["value"] for s in res["series"]] == [20.0]
    res = tsdb.eval_expr(store, "q", now=T0)
    assert [s["value"] for s in res["series"]] == [10.0, 20.0]


def test_eval_quantile_over_histogram_window_delta():
    store = SeriesStore(interval_s=1.0)
    # 10 fast observations first, then 10 slow ones; a window covering
    # only the slow delta must quantile near the slow bucket.
    store.ingest([_hist_fam("lat", [[0.01, 10], [0.1, 10]], 10, 0.05)],
                 now=T0)
    store.ingest([_hist_fam("lat", [[0.01, 10], [0.1, 20]], 20, 1.0)],
                 now=T0 + 100)
    res = tsdb.eval_expr(store, "quantile(0.5, lat[1m])", now=T0 + 100)
    v = res["series"][0]["value"]
    assert 0.01 < v <= 0.1, v
    # scalar companions exist with counter semantics
    res = tsdb.eval_expr(store, "rate(lat_count[10m])", now=T0 + 100)
    assert abs(res["series"][0]["value"] - 0.1) < 1e-6


def test_eval_scalar_fn_on_histogram_is_an_error():
    store = SeriesStore(interval_s=1.0)
    store.ingest([_hist_fam("lat", [[0.01, 1]], 1, 0.001)], now=T0)
    with pytest.raises(QueryError):
        tsdb.eval_expr(store, "rate(lat[1m])", now=T0)


def test_render_text_and_csv():
    store = SeriesStore(interval_s=1.0)
    store.ingest([_gauge_fam("q", 3.0, {"rank": "0"})], now=T0)
    res = tsdb.eval_expr(store, "q", now=T0)
    assert tsdb.render_text(res) == '{rank="0"} 3\n'
    assert tsdb.render_csv(res) == 'labels,value\n"rank=0",3\n'


# ---------------------------------------------------------------------------
# forecast
# ---------------------------------------------------------------------------

def test_forecast_recovers_linear_ramp():
    pts = [(T0 + i, 2.0 + 0.5 * i) for i in range(30)]
    v = tsdb.forecast_points(pts, 60.0)
    want = 2.0 + 0.5 * (29 + 60)
    assert abs(v - want) < 1e-6

def test_forecast_is_robust_to_an_outlier():
    pts = [(T0 + i, 1.0 * i) for i in range(30)]
    pts[13] = (T0 + 13, 500.0)    # one scrape hiccup
    v = tsdb.forecast_points(pts, 30.0)
    assert abs(v - (29 + 30)) < 2.0   # Theil-Sen shrugs it off

def test_forecast_degrades_gracefully_on_tiny_series():
    assert tsdb.forecast_points([], 30.0) is None
    assert tsdb.forecast_points([(T0, 4.0)], 30.0) == 4.0
    assert tsdb.forecast_points([(T0, 4.0), (T0 + 1, 5.0)], 30.0) == 5.0


def test_forecast_expr_through_the_query_layer():
    store = SeriesStore(interval_s=1.0)
    for i in range(20):
        store.ingest([_gauge_fam("q", 0.5 * i)], now=T0 + i)
    res = tsdb.eval_expr(store, "forecast(q[60s], 30)", now=T0 + 19)
    assert abs(res["series"][0]["value"] - (9.5 + 15.0)) < 1e-6


# ---------------------------------------------------------------------------
# alert engine state machine (FakeClock => fully deterministic)
# ---------------------------------------------------------------------------

def _alert_engine(spec, store, clk):
    return alerts.AlertEngine(alerts.parse_rules(spec), store=store,
                              clock=clk)


def _set_queue(store, clk, v):
    store.ingest([_gauge_fam("q", v)], now=clk())


def test_alert_parse_grammar():
    rules = alerts.parse_rules(
        "queue: avg_over_time(hvd_serving_queue_depth[1m]) > 8 "
        "for 30s : warn; burn: max_over_time(b[5m]) >= 14.4 : page; "
        "floor: q < 1")
    assert [(r.name, r.op, r.threshold, r.for_s, r.severity)
            for r in rules] == [
        ("queue", ">", 8.0, 30.0, "warn"),
        ("burn", ">=", 14.4, 0.0, "page"),
        ("floor", "<", 1.0, 0.0, "warn"),
    ]

@pytest.mark.parametrize("bad", [
    "rate(m[1m]) > 2",          # no name
    "a: m >",                   # no threshold
    "a: m > 1 : sideways",      # bad severity is not silently dropped
    "a: nope(m[1m]) > 1",       # expression must parse
    "a: m > 1; a: m > 2",       # duplicate names
])
def test_alert_parse_rejects_malformed(bad):
    with pytest.raises(QueryError):
        alerts.parse_rules(bad)


def test_alert_pending_hold_then_firing_then_resolve():
    clk = FakeClock()
    store = SeriesStore(interval_s=1.0)
    eng = _alert_engine("hot: q > 8 for 10s : crit", store, clk)
    _set_queue(store, clk, 9.0)
    eng.tick()
    assert eng.status()["alerts"][0]["state"] == "pending"
    clk.advance(5)
    _set_queue(store, clk, 9.5)
    eng.tick()     # held only 5s of 10s
    assert eng.status()["alerts"][0]["state"] == "pending"
    clk.advance(5)
    _set_queue(store, clk, 9.5)
    eng.tick()     # 10s held: fires
    st = eng.status()["alerts"][0]
    assert st["state"] == "firing" and st["fired_total"] == 1
    clk.advance(1)
    _set_queue(store, clk, 2.0)
    eng.tick()
    st = eng.status()["alerts"][0]
    assert st["state"] == "inactive" and st["resolved_total"] == 1

def test_alert_flap_inside_hold_never_fires():
    clk = FakeClock()
    store = SeriesStore(interval_s=1.0)
    eng = _alert_engine("hot: q > 8 for 10s", store, clk)
    for v in (9.0, 2.0, 9.0, 2.0, 9.0, 2.0):
        _set_queue(store, clk, v)
        eng.tick()
        clk.advance(4)
    st = eng.status()["alerts"][0]
    assert st["fired_total"] == 0 and st["state"] != "firing"


def test_alert_zero_hold_fires_immediately_and_sets_gauges():
    clk = FakeClock()
    store = SeriesStore(interval_s=1.0)
    eng = _alert_engine("hot_now: q >= 5 : page", store, clk)
    _set_queue(store, clk, 5.0)
    eng.tick()
    assert eng.status()["alerts"][0]["state"] == "firing"
    snap = {f["name"]: f for f in REGISTRY.snapshot()}
    [s] = [s for s in snap["hvd_alerts_firing"]["samples"]
           if s["labels"].get("alert") == "hot_now"]
    assert s["value"] == 1.0 and s["labels"]["severity"] == "page"
    # and the transition is on the flight-recorder ring
    kinds = [(e["kind"], e["name"]) for e in flightrec.RECORDER.snapshot()]
    assert ("alert_fired", "hot_now") in kinds


def test_alert_lifecycle_is_deterministic():
    """Same inputs => same transition sequence, twice over."""
    def run():
        clk = FakeClock()
        store = SeriesStore(interval_s=1.0)
        eng = _alert_engine("hot: q > 8 for 6s", store, clk)
        seen = []
        for v in (9, 9, 9, 2, 9, 9, 9, 9, 1):
            _set_queue(store, clk, v)
            eng.tick()
            seen.append(eng.status()["alerts"][0]["state"])
            clk.advance(3)
        return seen
    assert run() == run()
    assert run() == ["pending", "pending", "firing", "inactive",
                     "pending", "pending", "firing", "firing",
                     "inactive"]


def test_alert_lt_comparison_alerts_on_min_series():
    clk = FakeClock()
    store = SeriesStore(interval_s=1.0)
    store.ingest([_gauge_fam("q", 9.0, {"rank": "0"}),
                  _gauge_fam("q", 0.2, {"rank": "1"})], now=clk())
    eng = _alert_engine("starved: q < 1", store, clk)
    eng.tick()
    st = eng.status()["alerts"][0]
    assert st["state"] == "firing" and st["value"] == 0.2


# ---------------------------------------------------------------------------
# predictive autoscaling
# ---------------------------------------------------------------------------

def _ramp_families(depth):
    return [
        {"name": "horovod_tpu_rank_snapshot_age_seconds", "type": "gauge",
         "help": "", "labelnames": ("rank", "stale"),
         "samples": [{"labels": {"rank": "0", "stale": "false"},
                      "value": 0.0}]},
        _gauge_fam("hvd_serving_queue_depth", depth, {"rank": "0"}),
    ]


def test_signals_carry_queue_forecast_from_store():
    store = SeriesStore(interval_s=1.0)
    for i in range(12):
        store.ingest(_ramp_families(0.5 * i), now=T0 + i)
    sig = signals_from_families(
        _ramp_families(5.5), current_np=2, available_slots=4,
        store=store, forecast_horizon_s=30.0, now=T0 + 11)
    assert sig.queue_forecast is not None
    assert abs(sig.queue_forecast - (5.5 + 15.0)) < 1e-6
    # horizon 0 = off
    sig = signals_from_families(
        _ramp_families(5.5), current_np=2, available_slots=4,
        store=store, forecast_horizon_s=0.0, now=T0 + 11)
    assert sig.queue_forecast is None

def test_policy_grows_on_predicted_breach_before_threshold():
    clk = FakeClock()
    pol = ScalePolicy(PolicyConfig(
        min_np=2, max_np=4, queue_high=8.0, forecast_horizon_s=30.0,
        scale_up_cooldown_s=0.0), clock=clk)
    d = pol.decide(Signals(current_np=2, available_slots=4,
                           queue_depth=3.0, queue_forecast=16.0))
    assert d.action == "grow_predicted" and d.target_np == 4
    assert "forecast" in d.reason


def test_policy_predicted_grow_respects_cooldown_and_capacity():
    clk = FakeClock()
    pol = ScalePolicy(PolicyConfig(
        min_np=2, max_np=4, queue_high=8.0, forecast_horizon_s=30.0,
        scale_up_cooldown_s=30.0), clock=clk)
    sig = Signals(current_np=2, available_slots=4, queue_depth=3.0,
                  queue_forecast=16.0)
    assert pol.decide(sig).action == "hold"     # construction stamp
    clk.advance(31)
    d = pol.decide(sig)
    assert d.action == "grow_predicted"
    clk.advance(5)
    assert pol.decide(sig).action == "hold"     # shared up-cooldown
    # at capacity: hold, not grow
    clk.advance(31)
    d = pol.decide(Signals(current_np=4, available_slots=4,
                           queue_depth=3.0, queue_forecast=16.0))
    assert d.action == "hold" and "capacity" in d.reason


def test_policy_forecast_off_by_default():
    pol = ScalePolicy(PolicyConfig(min_np=2, max_np=4, queue_high=8.0,
                                   scale_up_cooldown_s=0.0),
                      clock=FakeClock())
    d = pol.decide(Signals(current_np=2, available_slots=4,
                           queue_depth=3.0, queue_forecast=16.0))
    assert d.action == "hold"   # hysteresis band, forecast ignored


def test_controller_predictive_grow_end_to_end():
    """Ramping queue through the real controller + its tsdb history:
    grow_predicted fires (and bumps) while the instantaneous depth is
    still below queue_high."""
    clk = FakeClock()
    depth = [0.0]
    bumps = []
    pol = ScalePolicy(PolicyConfig(
        min_np=2, max_np=4, queue_high=8.0, forecast_horizon_s=30.0,
        scale_up_cooldown_s=0.0), clock=clk)
    ctl = AutoscaleController(
        pol, current_np=2, collect=lambda: _ramp_families(depth[0]),
        bump=lambda: bumps.append(1), capacity=lambda: 4,
        store=SeriesStore(interval_s=1.0), clock=clk)
    fired_at = None
    for _ in range(20):
        d = ctl.poll_once()
        if d.action == "grow_predicted":
            fired_at = depth[0]
            break
        clk.advance(1.0)
        depth[0] += 0.5
    assert fired_at is not None and fired_at < 8.0, fired_at
    assert bumps == [1]


# ---------------------------------------------------------------------------
# HTTP surface: /query, /alertz, the route table
# ---------------------------------------------------------------------------

@pytest.fixture
def armed_tier():
    tsdb.arm(interval_s=3600.0, retention_s=7200.0)  # manual ticks only
    srv = server.MetricsServer(0, addr="127.0.0.1")
    try:
        yield srv
    finally:
        srv.close()
        alerts.disarm()
        tsdb.disarm()


def _get(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10).read().decode()


def test_query_endpoint_rate_within_1e6_of_analytic(armed_tier):
    """Acceptance: GET /query?expr=rate(...[1m]) over a synthetic
    counter driven at a known rate, including across a reset."""
    import time as _time
    c = REGISTRY.counter("tsdb_http_events_total", "query acceptance")
    now = _time.time()
    c.inc(3)
    tsdb.sample_now(now - 20)
    c.inc(6)
    tsdb.sample_now(now - 10)
    blob = json.loads(_get(
        armed_tier.port, "/query.json?expr=" + urllib.parse.quote(
            "rate(tsdb_http_events_total[1m])")))
    assert abs(blob["series"][0]["value"] - 0.6) < 1e-6
    # reset: registry reset drops the counter to a lower value
    fams = [_counter_fam("tsdb_http_events_total", 2.0)]
    tsdb.local_store().ingest(fams, now=now)
    blob = json.loads(_get(
        armed_tier.port, "/query.json?expr=" + urllib.parse.quote(
            "rate(tsdb_http_events_total[1m])")))
    analytic = (6.0 + 2.0) / 20.0
    assert abs(blob["series"][0]["value"] - analytic) < 1e-6
    # text + csv renderings answer too
    assert _get(armed_tier.port, "/query.csv?expr=" + urllib.parse.quote(
        "rate(tsdb_http_events_total[1m])")).startswith("labels,value")

def test_query_endpoint_rejects_bad_exprs_with_400(armed_tier):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(armed_tier.port, "/query?expr=" +
             urllib.parse.quote("nope(m[1m])"))
    assert ei.value.code == 400


def test_alertz_endpoint_serves_engine_state(armed_tier):
    REGISTRY.gauge("tsdb_http_alert_gauge", "alertz acceptance").set(9.0)
    tsdb.sample_now()
    eng = alerts.arm("http_hot: tsdb_http_alert_gauge > 5 : warn",
                     tick_s=3600.0)
    eng.tick()
    blob = json.loads(_get(armed_tier.port, "/alertz.json"))
    assert blob["firing"] == 1
    [a] = [a for a in blob["alerts"] if a["alert"] == "http_hot"]
    assert a["state"] == "firing"
    assert "http_hot" in _get(armed_tier.port, "/alertz")


def test_alertz_answers_503_when_unarmed(armed_tier):
    alerts.disarm()
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(armed_tier.port, "/alertz")
    assert ei.value.code == 503


def test_route_table_drives_index_and_404():
    """Satellite: the 404 help and the / index derive from one route
    table — every route (incl. /tracez.json, the one the old string
    missed) appears in both."""
    srv = server.MetricsServer(0, addr="127.0.0.1")
    try:
        index = _get(srv.port, "/")
        for path, _ in server.ROUTES:
            assert path in index, (path, index)
        try:
            _get(srv.port, "/definitely-not-a-route")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
            body = e.read().decode()
            for path, _ in server.ROUTES:
                assert path in body, (path, body)
    finally:
        srv.close()


def test_query_unarmed_is_a_clear_error():
    tsdb.disarm()
    srv = server.MetricsServer(0, addr="127.0.0.1")
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.port, "/query?expr=up")
        assert ei.value.code == 400
        assert "not armed" in ei.value.read().decode()
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# flight-recorder tsdb tail
# ---------------------------------------------------------------------------

def test_flightrec_bundle_carries_tsdb_tail(tmp_path):
    import time as _time
    # hour-long interval => after its arm-time tick the background
    # sampler never fires again during the test; the wide retention
    # keeps the raw ring deep enough.  Timestamps run FORWARD from real
    # now (the arm-time tick already stamped now, and earlier suite
    # tests may have seeded the series) so none are rejected as
    # out-of-order.
    tsdb.arm(interval_s=3600.0, retention_s=86400.0)
    try:
        g = REGISTRY.gauge("hvd_serving_queue_depth", "queue depth")
        base = _time.time() + 1.0
        for i in range(5):
            g.set(float(i))
            tsdb.sample_now(base + i)
        path = str(tmp_path / "bundle.json")
        assert flightrec.RECORDER.dump(path, reason="manual") == path
        b = json.loads(open(path).read())
        tails = {s["name"]: s["points"] for s in b["tsdb"]["series"]}
        assert "hvd_serving_queue_depth" in tails, b["tsdb"]
        assert [p[1] for p in tails["hvd_serving_queue_depth"]][-5:] == \
            [0.0, 1.0, 2.0, 3.0, 4.0]
    finally:
        tsdb.disarm()


def test_flightrec_tsdb_key_empty_when_unarmed(tmp_path):
    tsdb.disarm()
    path = str(tmp_path / "bundle.json")
    assert flightrec.RECORDER.dump(path, reason="manual") == path
    assert json.loads(open(path).read())["tsdb"] == {}
