"""ZeRO-1 sharded optimizer (optim/zero + optim/partition) and the
bucketed backward overlap layer (ops/sched/buckets).

The load-bearing contract: ``ZeroDistributedOptimizer`` produces
BIT-identical updated parameters to the dense ``DistributedOptimizer``
on this backend — fp32 across all three ``HOROVOD_TPU_SCHED_MODE``s, and
the int8 wire too (bucket flattening pads every leaf to the dense chunk
layout's ``n * block`` unit, so quant block boundaries and shared scales
land identically, and the shard chain replays the dense post-combine
requantization).  Parity over the real negotiated transport lives in
tests/mp_sched_worker.py ``main_zero`` / test_runner.py (the CI
``zero1-parity`` job).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.jaxcompat import shard_map
from horovod_tpu.ops.compression import Compression
from horovod_tpu.optim import partition as PP
from horovod_tpu.optim import zero as zero_mod

N = 8


@pytest.fixture
def sched_cfg():
    cfg = hvd.global_state().config
    old = (cfg.sched_mode, cfg.sched_chunks, cfg.quant_min_bytes,
           cfg.bucket_bytes, cfg.zero)
    yield cfg
    (cfg.sched_mode, cfg.sched_chunks, cfg.quant_min_bytes,
     cfg.bucket_bytes, cfg.zero) = old


def _mapped_update(tx, grads_per_rank, params):
    """tx.init outside the mapped context, tx.update inside — the
    train-step shape ZeRO documents (init's zero-valued shard template
    is exact for scale_by_* style inits)."""
    mesh = hvd.mesh()
    opt_state = tx.init(params)

    def step(g, p):
        local = jax.tree.map(lambda a: a[0], g)
        updates, _ = tx.update(local, opt_state, p)
        return jax.tree.map(lambda u: u[None], updates)

    fn = shard_map(step, mesh=mesh, in_specs=(P("hvd"), P()),
                   out_specs=P("hvd"), check_vma=False)
    return jax.jit(fn)(grads_per_rank, params)


def _params_and_grads(seed=0):
    params = {"w": jnp.zeros((3000,), jnp.float32),
              "b": jnp.ones((37,), jnp.float32)}
    grads = {
        "w": hvd.per_rank(
            [np.random.RandomState(seed + r).randn(3000).astype(np.float32)
             for r in range(N)]),
        "b": hvd.per_rank(
            [np.random.RandomState(seed + 50 + r).randn(37)
             .astype(np.float32) for r in range(N)]),
    }
    return params, grads


# ---------------------------------------------------------------------------
# parity vs the dense DistributedOptimizer
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["monolithic", "decomposed", "compiled"])
def test_zero_parity_all_sched_modes(sched_cfg, mode):
    """Updated parameters bit-identical to the dense wrapper in every
    sched mode: psum_scatter performs the same per-element float ops as
    psum on this backend (decomposed/compiled), and the monolithic
    fallback reuses the dense ``_reduce_in_context`` verbatim before
    slicing the shard."""
    params, grads = _params_and_grads(seed=0)
    dense = hvd.DistributedOptimizer(optax.adam(1e-2))
    zero = hvd.ZeroDistributedOptimizer(optax.adam(1e-2))
    sched_cfg.sched_mode, sched_cfg.sched_chunks = mode, 3
    base = jax.tree.map(hvd.to_numpy, _mapped_update(dense, grads, params))
    got = jax.tree.map(hvd.to_numpy, _mapped_update(zero, grads, params))
    for k in base:
        assert np.array_equal(base[k], got[k]), k


def test_zero_compiled_stays_single_program(sched_cfg):
    """Compiled mode: the whole ZeRO step (rs -> sharded update ->
    param allgather) is ONE jitted program — the engine's per-unit
    schedule dispatch counter never moves (the invariant the CI
    zero1-parity job's zero-dispatch guard pins over real transport)."""
    from horovod_tpu.ops.sched.executor import _m_sched
    params, grads = _params_and_grads(seed=7)
    zero = hvd.ZeroDistributedOptimizer(optax.adam(1e-2))
    sched_cfg.sched_mode, sched_cfg.sched_chunks = "compiled", 3
    before = _m_sched.total()
    _mapped_update(zero, grads, params)
    assert _m_sched.total() == before


def test_zero_int8_parity_decomposed(sched_cfg):
    """int8 wire, decomposed: bit-identical to the DENSE int8 decomposed
    path — the bucket pads every leaf to the n*block unit, so quant
    block boundaries/shared scales match, and the shard chain replays
    the dense post-combine requantization roundtrip."""
    params, grads = _params_and_grads(seed=20)
    sched_cfg.sched_mode, sched_cfg.sched_chunks = "decomposed", 2
    sched_cfg.quant_min_bytes = 1024
    dense = hvd.DistributedOptimizer(optax.adam(1e-2),
                                     compression=Compression.int8)
    zero = hvd.ZeroDistributedOptimizer(optax.adam(1e-2),
                                        compression=Compression.int8)
    base = jax.tree.map(hvd.to_numpy, _mapped_update(dense, grads, params))
    got = jax.tree.map(hvd.to_numpy, _mapped_update(zero, grads, params))
    for k in base:
        assert np.array_equal(base[k], got[k]), k


def test_zero_bucket_split_keeps_parity(sched_cfg):
    """A small HOROVOD_TPU_BUCKET_BYTES splits the fp32 group into
    several buckets (each its own rs chain + param allgather); the math
    per bucket is unchanged, so parity stays bit-exact."""
    params, grads = _params_and_grads(seed=33)
    dense = hvd.DistributedOptimizer(optax.adam(1e-2))
    zero = hvd.ZeroDistributedOptimizer(optax.adam(1e-2),
                                        bucket_bytes=4096)
    plan = PP.build_plan(params, N, modes=["fp32", "fp32"],
                         block=512, chunks=2, bucket_bytes=4096)
    assert len(plan.buckets) > 1
    sched_cfg.sched_mode, sched_cfg.sched_chunks = "decomposed", 2
    base = jax.tree.map(hvd.to_numpy, _mapped_update(dense, grads, params))
    got = jax.tree.map(hvd.to_numpy, _mapped_update(zero, grads, params))
    for k in base:
        assert np.array_equal(base[k], got[k]), k


def test_zero_sum_op_parity(sched_cfg):
    params, grads = _params_and_grads(seed=41)
    dense = hvd.DistributedOptimizer(optax.sgd(1.0), op=hvd.Sum)
    zero = hvd.ZeroDistributedOptimizer(optax.sgd(1.0), op=hvd.Sum)
    sched_cfg.sched_mode = "decomposed"
    base = jax.tree.map(hvd.to_numpy, _mapped_update(dense, grads, params))
    got = jax.tree.map(hvd.to_numpy, _mapped_update(zero, grads, params))
    for k in base:
        assert np.array_equal(base[k], got[k]), k


# ---------------------------------------------------------------------------
# state sharding + gauge
# ---------------------------------------------------------------------------

def test_zero_state_bytes_gauge_shards_state():
    """The acceptance gauge: per-rank optimizer-state bytes under ZeRO
    stay at <= 1/n of the dense footprint plus shard-divisible padding
    (scalar leaves like Adam's step count don't shard)."""
    params = {"w": jnp.zeros((3000,), jnp.float32),
              "b": jnp.ones((37,), jnp.float32)}
    zero = hvd.ZeroDistributedOptimizer(optax.adam(1e-2))
    state = zero.init(params)
    zb = zero_mod._g_state_bytes.value
    assert zb == PP.shard_bytes(state)
    db = PP.shard_bytes(optax.adam(1e-2).init(params))
    # Padding bound: every leaf pads by < n elements, counted twice for
    # Adam's mu+nu, plus the unsharded count scalar.
    pad_allowance = 2 * len(params) * N * 4 + 64
    assert zb <= db / N + pad_allowance
    assert zb / db < 0.2    # way below dense; ~1/8 for these shapes


def test_zero_init_in_context_uses_true_shard(sched_cfg):
    """init INSIDE the mapped context slices the real parameter shard
    (value-dependent inner inits see true values, not the zero
    template) — and the end-to-end update still matches dense."""
    params, grads = _params_and_grads(seed=55)
    sched_cfg.sched_mode = "decomposed"
    mesh = hvd.mesh()
    dense = hvd.DistributedOptimizer(optax.adam(1e-2))
    zero = hvd.ZeroDistributedOptimizer(optax.adam(1e-2))

    def step(tx):
        def body(g, p):
            local = jax.tree.map(lambda a: a[0], g)
            st = tx.init(p)
            updates, _ = tx.update(local, st, p)
            return jax.tree.map(lambda u: u[None], updates)
        fn = shard_map(body, mesh=mesh, in_specs=(P("hvd"), P()),
                       out_specs=P("hvd"), check_vma=False)
        return jax.tree.map(hvd.to_numpy, jax.jit(fn)(grads, params))

    base, got = step(dense), step(zero)
    for k in base:
        assert np.array_equal(base[k], got[k]), k


# ---------------------------------------------------------------------------
# restrictions / config dispatch
# ---------------------------------------------------------------------------

def test_zero_rejects_unsupported():
    with pytest.raises(NotImplementedError):
        hvd.ZeroDistributedOptimizer(optax.sgd(1.0), partition=2)
    with pytest.raises(ValueError):
        hvd.ZeroDistributedOptimizer(optax.sgd(1.0), op=hvd.Adasum)


def test_zero_update_requires_mapped_context():
    zero = hvd.ZeroDistributedOptimizer(optax.sgd(1.0))
    params = {"w": jnp.zeros((16,), jnp.float32)}
    state = zero.init(params)
    with pytest.raises(ValueError, match="mapped context"):
        zero.update(params, state, params)


def test_zero_from_config_dispatch(sched_cfg):
    """HOROVOD_TPU_ZERO flips train-step builders between the dense and
    the ZeRO wrapper through one entry point."""
    from horovod_tpu.optim.zero import from_config
    params = {"w": jnp.zeros((16,), jnp.float32)}
    sched_cfg.zero = True
    tx = from_config(optax.sgd(1.0))
    st = tx.init(params)
    with pytest.raises(ValueError, match="mapped context"):
        tx.update(params, st, params)   # the ZeRO signature
    sched_cfg.zero = False
    tx = from_config(optax.sgd(1.0), bucket_bytes=4096, num_shards=N)
    st = tx.init(params)                # dense: extra kwargs dropped


# ---------------------------------------------------------------------------
# partition plan unit behavior
# ---------------------------------------------------------------------------

def test_partition_plan_pads_to_chunk_units():
    params = {"w": jnp.zeros((3000,), jnp.float32),
              "b": jnp.ones((37,), jnp.float32)}
    plan = PP.build_plan(params, N, modes=["fp32", "fp32"], block=512,
                         chunks=2)
    assert plan.n == N
    for b in plan.buckets:
        assert b.numel % N == 0
        assert b.shard == b.numel // N
        layout = PP.bucket_layout(plan, b)
        # Unit-multiple bucket: chunk_layout never re-pads.
        assert sum(layout) == b.numel
    # Quant buckets pad to n*block so block boundaries match dense.
    plan_q = PP.build_plan(params, N, modes=["int8", "fp32"], block=512,
                           chunks=2)
    wq = next(b for b in plan_q.buckets if b.mode == "int8")
    assert wq.numel % (N * 512) == 0


def test_partition_shard_roundtrip():
    """extract_shard per rank -> assemble_from_shards reconstructs the
    flat bucket exactly (the allgather-side identity the update relies
    on)."""
    params = {"w": jnp.arange(3000, dtype=jnp.float32),
              "b": jnp.arange(37, dtype=jnp.float32)}
    plan = PP.build_plan(params, N, modes=["fp32", "fp32"], block=512,
                         chunks=3)
    leaves = jax.tree.flatten(params)[0]
    for bucket in plan.buckets:
        layout = PP.bucket_layout(plan, bucket)
        flat = PP.flatten_bucket(bucket, leaves)
        shards = [PP.extract_shard(flat, r, layout, N) for r in range(N)]
        gathered = jnp.stack(shards).reshape(-1)
        back = PP.assemble_from_shards(gathered, layout, N)
        assert np.array_equal(np.asarray(back), np.asarray(flat))
        # And the leaves unflatten to their original values.
        for idx, arr in PP.unflatten_bucket(bucket, back):
            assert np.array_equal(np.asarray(arr),
                                  np.asarray(leaves[idx]))


# ---------------------------------------------------------------------------
# bucketed backward overlap (ops/sched/buckets)
# ---------------------------------------------------------------------------

def test_plan_buckets_groups_by_dtype_and_size():
    from horovod_tpu.ops.sched.buckets import plan_buckets
    leaves = [jnp.zeros((1024,), jnp.float32),     # 4096 B
              jnp.zeros((1024,), jnp.float32),
              jnp.zeros((8,), jnp.int32),          # different dtype
              jnp.zeros((1024,), jnp.float32)]
    # Uncapped: one bucket per dtype, pytree order preserved.
    assert plan_buckets(leaves, 0) == [[0, 1, 3], [2]]
    # 8 KB cap: two fp32 leaves fit, the third spills.
    assert plan_buckets(leaves, 8192) == [[0, 1], [2], [3]]
    # One oversized leaf still gets its own bucket.
    assert plan_buckets([jnp.zeros((65536,), jnp.float32)], 8192) == [[0]]


def test_bucketed_distributed_gradients_matches_dense(sched_cfg):
    """Eager bucketed reduction: identical results to the unbucketed
    engine path, and the per-bucket ASAP dispatch realizes comm/compute
    overlap the executor's gauge reports (>0) — the acceptance assert
    for the eager path."""
    from horovod_tpu.ops.sched.executor import _m_overlap
    sched_cfg.sched_mode, sched_cfg.sched_chunks = "decomposed", 4
    grads = {
        f"p{i}": hvd.per_rank(
            [np.random.RandomState(100 * i + r).randn(8192)
             .astype(np.float32) for r in range(N)])
        for i in range(3)
    }
    _m_overlap.set(0.0)
    out = hvd.bucketed_distributed_gradients(grads, bucket_bytes=40000)
    for i in range(3):
        want = np.mean(np.stack(
            [np.random.RandomState(100 * i + r).randn(8192)
             .astype(np.float32) for r in range(N)]), axis=0)
        np.testing.assert_allclose(hvd.to_numpy(out[f"p{i}"]), want,
                                   rtol=1e-6, atol=1e-6)
    assert _m_overlap.value > 0.0


def test_attach_gradient_reduction_reduces_per_bucket(sched_cfg):
    """In-jit bucket boundaries: jax.grad through the attached params
    yields already-averaged gradients, bit-equal to the explicit pmean
    (fp32 chains are bit-exact vs monolithic by the sched contract)."""
    sched_cfg.sched_mode = "decomposed"
    from horovod_tpu.ops.sched.buckets import attach_gradient_reduction
    mesh = hvd.mesh()
    params = {"w": jnp.ones((2048,), jnp.float32),
              "v": jnp.ones((512,), jnp.float32)}
    xs = hvd.per_rank([np.random.RandomState(r).randn(2048)
                       .astype(np.float32) for r in range(N)])

    def step(x, p):
        xl = x[0]

        def loss(p_):
            wp = attach_gradient_reduction(p_, "hvd", chunks=2,
                                           bucket_bytes=4096)
            return jnp.sum(wp["w"] * xl) + 3.0 * jnp.sum(wp["v"])

        g = jax.grad(loss)(p)
        return jax.tree.map(lambda u: u[None], g)

    fn = shard_map(step, mesh=mesh, in_specs=(P("hvd"), P()),
                   out_specs=P("hvd"), check_vma=False)
    got = jax.tree.map(hvd.to_numpy, jax.jit(fn)(xs, params))
    want_w = np.mean(np.asarray(hvd.to_numpy(xs)), axis=0)
    for r in range(N):
        assert np.array_equal(got["w"][r], want_w)
        np.testing.assert_allclose(got["v"][r], np.full((512,), 3.0))


def test_engine_fusion_respects_bucket_cap(sched_cfg):
    """cfg.bucket_bytes caps the engine's fusion grouping: two 4 KB
    entries that would fuse under the 64 MB threshold stay separate
    collectives under a 4 KB bucket cap."""
    engine = hvd.global_state().engine
    sched_cfg.bucket_bytes = 4096
    a = hvd.per_rank([np.full((1024,), float(r), np.float32)
                      for r in range(N)])
    b = hvd.per_rank([np.full((1024,), 2.0 * r, np.float32)
                      for r in range(N)])
    h1 = hvd.allreduce_async(a, hvd.Average)
    h2 = hvd.allreduce_async(b, hvd.Average)
    out1, out2 = h1.wait(), h2.wait()
    np.testing.assert_allclose(hvd.to_numpy(out1), np.full((1024,), 3.5))
    np.testing.assert_allclose(hvd.to_numpy(out2), np.full((1024,), 7.0))
    assert engine is not None


# ---------------------------------------------------------------------------
# satellite regressions (optim/distributed)
# ---------------------------------------------------------------------------

def test_distributed_gradients_engine_side_decompress_runs_once():
    """Regression: engine-side (quantized) compressors dequantize inside
    the fused collective — the host-side decompress must NOT run again
    on the engine output (a lossy decompress would corrupt it)."""
    calls = {"n": 0}

    class SpyInt8(Compression.int8):
        @staticmethod
        def decompress(tensor, ctx):
            calls["n"] += 1
            return tensor

    grads = {"g": hvd.per_rank([np.full((512,), float(r), np.float32)
                                for r in range(N)])}
    out = hvd.distributed_gradients(grads, compression=SpyInt8)
    assert calls["n"] == 0
    np.testing.assert_allclose(hvd.to_numpy(out["g"]),
                               np.full((512,), 3.5), rtol=0.05)
    # Bucketed twin shares the routing rule.
    out2 = hvd.bucketed_distributed_gradients(grads, compression=SpyInt8)
    assert calls["n"] == 0
    np.testing.assert_allclose(hvd.to_numpy(out2["g"]),
                               np.full((512,), 3.5), rtol=0.05)


def test_aggregation_accumulator_keeps_grad_dtype():
    """Regression: bf16 params + fp32 grads — the local-aggregation
    accumulator must carry the GRADIENT dtype, not round every
    micro-batch onto the bf16 grid (zeros_like(params) seeds it bf16)."""
    mesh = hvd.mesh()
    params = {"w": jnp.zeros((4,), jnp.bfloat16)}
    tx = hvd.DistributedOptimizer(optax.sgd(1.0),
                                  backward_passes_per_step=2)
    # 1.0 then 2**-10: a bf16 accumulator would round the sum to 1.0.
    g1 = np.full((4,), 1.0, np.float32)
    g2 = np.full((4,), 2.0 ** -10, np.float32)

    def step(gs, p):
        state = tx.init(p)
        outs = []
        for i in range(2):
            updates, state = tx.update({"w": gs[0, i]}, state, p)
            outs.append(updates["w"])
        return jnp.stack(outs)[None]

    grads = hvd.per_rank([np.stack([g1, g2])] * N)
    fn = shard_map(step, mesh=mesh, in_specs=(P("hvd"), P()),
                   out_specs=P("hvd"), check_vma=False)
    outs = hvd.to_numpy(jax.jit(fn)(grads, params))  # [N, 2, 4]
    np.testing.assert_allclose(outs[:, 0], 0.0)
    exact = -(1.0 + 2.0 ** -10) / 2.0
    assert outs.dtype == np.float32
    np.testing.assert_allclose(outs[:, 1], exact, rtol=0, atol=0)


@pytest.mark.parametrize("mode", ["decomposed", "compiled"])
def test_backward_passes_with_sched_modes(sched_cfg, mode):
    """Satellite: backward_passes_per_step > 1 composed with the
    decomposed/compiled schedules — off-cycle updates zero, the firing
    step bit-equal to the monolithic aggregation path."""
    params = {"w": jnp.zeros((2048,), jnp.float32)}
    tx = hvd.DistributedOptimizer(optax.sgd(1.0),
                                  backward_passes_per_step=2)
    mesh = hvd.mesh()

    def step(gs, p):
        state = tx.init(p)
        outs = []
        for i in range(2):
            updates, state = tx.update({"w": gs[0, i]}, state, p)
            outs.append(updates["w"])
        return jnp.stack(outs)[None]

    grads = hvd.per_rank([
        np.stack([np.random.RandomState(1000 + 2 * r + i).randn(2048)
                  .astype(np.float32) for i in range(2)])
        for r in range(N)])
    fn = shard_map(step, mesh=mesh, in_specs=(P("hvd"), P()),
                   out_specs=P("hvd"), check_vma=False)
    base = hvd.to_numpy(jax.jit(fn)(grads, params))
    sched_cfg.sched_mode, sched_cfg.sched_chunks = mode, 2
    got = hvd.to_numpy(jax.jit(fn)(grads, params))
    np.testing.assert_allclose(got[:, 0], 0.0)
    assert np.array_equal(got, base)
